"""Live serving observability plane (ISSUE 14): the in-flight query table
(SHOW QUERIES / /v1/queries / CANCEL QUERY), the HBM ledger, cross-query
causality links (flow events), the always-on flight recorder (DSQL501
vocabulary + /v1/debug/events + failure auto-flush), streamed progress
gauges, queue-wait attribution, and store bounds under concurrent
eviction-racing-readers load.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu import config as config_module
from dask_sql_tpu.observability import (
    ProfileStore,
    QueryTrace,
    TraceStore,
    activate,
    flight,
    merge_chrome_traces,
    render_prometheus,
)
from dask_sql_tpu.serving.metrics import MetricsRegistry

pytestmark = pytest.mark.observability


@pytest.fixture(autouse=True)
def _fresh_state():
    """The flight recorder and config are process-global; every test
    starts clean and restores what it touched."""
    saved = config_module.config.effective_items()
    flight.RECORDER.clear()
    yield
    config_module.config.update(dict(saved))
    flight.RECORDER.clear()


def _ctx(rows=64, name="lt"):
    c = Context()
    c.create_table(name, pd.DataFrame({
        "a": np.arange(rows, dtype=np.int64),
        "b": np.arange(rows, dtype=np.float64) * 1.5,
    }))
    return c


# ------------------------------------------------------- in-flight table
def test_show_queries_lists_finished_query_with_rung_and_family():
    c = _ctx()
    c.sql("SELECT SUM(a) AS s FROM lt", return_futures=False)
    qid = c.last_trace.qid
    df = c.sql("SHOW QUERIES", return_futures=False)
    assert list(df.columns) == ["Qid", "Field", "Value"]
    rows = {(r.Field): r.Value for r in df.itertuples() if r.Qid == qid}
    assert rows["state"] == "done"
    assert rows["class"] == "interactive"
    assert "rung" in rows and rows["rung"]
    assert rows["sql"].startswith("SELECT SUM(a)")
    # the HBM-ledger summary block rides along under the pseudo-qid
    ledger_fields = {r.Field for r in df.itertuples() if r.Qid == "(ledger)"}
    assert {"reservedBytes", "resultCacheBytes", "tableBytes",
            "headroomBytes", "driftBytes"} <= ledger_fields


def test_show_queries_python_and_native_paths_agree():
    c = _ctx()
    c.sql("SELECT a FROM lt WHERE a > 3", return_futures=False)
    native = c.sql("SHOW QUERIES", return_futures=False)
    python = c.sql("SHOW QUERIES", return_futures=False,
                   config_options={"sql.native.binder": "off"})
    assert list(native.columns) == list(python.columns)
    # same qids visible through both parser/binder paths
    assert set(native["Qid"]) == set(python["Qid"])


def test_show_queries_like_filters_on_qid_and_field():
    c = _ctx()
    c.sql("SELECT a FROM lt", return_futures=False)
    qid = c.last_trace.qid
    only_ledger = c.sql("SHOW QUERIES LIKE 'ledger'", return_futures=False)
    assert set(only_ledger["Qid"]) == {"(ledger)"}
    mine = c.sql(f"SHOW QUERIES LIKE '{qid[:12]}'", return_futures=False)
    assert set(mine["Qid"]) == {qid}


def test_cancel_query_unknown_qid_reports_false():
    c = _ctx()
    df = c.sql("CANCEL QUERY 'no-such-query'", return_futures=False)
    assert list(df.columns) == ["Qid", "Cancelled"]
    assert list(df["Cancelled"]) == ["false"]
    # the request itself is still on the postmortem timeline
    assert any(e["event"] == "query.cancel"
               and e.get("qid") == "no-such-query"
               for e in flight.RECORDER.events())


def _slow_ctx(rows=4000, sleep_s=0.002):
    c = _ctx(rows=rows, name="slow_t")

    def crawl(a):
        time.sleep(sleep_s)
        return a

    c.register_function(crawl, "crawl", [("a", np.int64)], np.int64,
                        row_udf=True)
    return c


def test_cancel_query_statement_stops_running_query():
    """CANCEL QUERY (SQL path) cancels a Context-API query mid-run via its
    live-registry ticket: the executor's per-row checkpoint raises."""
    c = _slow_ctx()
    errors = []

    def run():
        try:
            c.sql("SELECT crawl(a) AS x FROM slow_t", return_futures=False)
        except BaseException as exc:  # noqa: BLE001 - asserted below
            errors.append(exc)

    t = threading.Thread(target=run)
    t.start()
    try:
        entry = None
        deadline = time.time() + 10.0
        while time.time() < deadline:
            live = c.live_queries.live_entries()
            if live and live[0].state == "running":
                entry = live[0]
                break
            time.sleep(0.005)
        assert entry is not None, "query never appeared in the live table"
        df = c.sql(f"CANCEL QUERY '{entry.qid}'", return_futures=False)
        assert list(df["Cancelled"]) == ["true"]
    finally:
        t.join(20.0)
    assert not t.is_alive()
    assert errors, "query was not cancelled"
    from dask_sql_tpu.serving.admission import QueryCancelledError

    assert isinstance(errors[0], QueryCancelledError)
    assert c.live_queries.get(entry.qid).state == "cancelled"
    events = flight.RECORDER.events(name="query.cancel")
    assert any(e.get("qid") == entry.qid for e in events)


def test_live_entry_records_stage_rung_and_measured_bytes():
    c = _ctx()
    c.sql("SELECT SUM(b) AS s FROM lt", return_futures=False)
    entry = c.live_queries.entries()[-1]
    assert entry.state == "done"
    assert entry.stage == "execute"
    assert entry.rung  # the ladder stamped the answering rung
    assert entry.measured_bytes is not None and entry.measured_bytes > 0


# --------------------------------------------------- streamed progress
def _stream_ctx(n=40_000):
    c = Context()
    c.config.update({"serving.cache.enabled": False})
    rng = np.random.RandomState(7)
    c.create_table("t", pd.DataFrame({
        "k": rng.randint(0, 5, n).astype(np.int64),
        "v": rng.randint(0, 1000, n).astype(np.int64),
    }))
    from dask_sql_tpu.serving.cache import table_nbytes

    budget = table_nbytes(c.schema["root"].tables["t"].table) // 3
    return c, budget, n


def test_streamed_query_updates_progress_gauges_and_live_entry():
    c, budget, n = _stream_ctx()
    c.sql("SELECT k, SUM(v) AS s FROM t GROUP BY k", return_futures=False,
          config_options={"serving.admission.max_estimated_bytes": budget})
    parts = c.metrics.counter("serving.stream.partitions")
    assert parts > 1
    gauges = c.metrics.snapshot()["gauges"]
    assert gauges["serving.stream.partitions_done"] == parts
    assert gauges["serving.stream.rows_done"] == n
    entry = c.live_queries.entries()[-1]
    assert entry.stream_partitions_done == parts
    assert entry.stream_partitions_total == parts
    assert entry.stream_rows_done == n
    # SHOW QUERIES renders the progress fields
    df = c.sql("SHOW QUERIES", return_futures=False)
    rows = {r.Field: r.Value for r in df.itertuples() if r.Qid == entry.qid}
    assert rows["stream.partitions"] == f"{parts}/{parts}"
    assert rows["stream.rows"] == f"{n}/{n}"


def test_streamed_partitions_are_detail_spans_under_execute():
    c, budget, _ = _stream_ctx()
    c.sql("SELECT k, SUM(v) AS s FROM t GROUP BY k", return_futures=False,
          config_options={"serving.admission.max_estimated_bytes": budget})
    tr = c.last_trace
    parts = [s for s in tr.spans if s.name == "stream_partition"]
    assert len(parts) > 1
    assert all(s.kind == "detail" and s.parent == "execute" for s in parts)


# ------------------------------------------------------------ HBM ledger
def test_ledger_reconciles_and_sums_consistently():
    c, budget, _ = _stream_ctx()
    config_module.config.update(
        {"serving.admission.max_estimated_bytes": budget * 100})
    snap = c.ledger.snapshot()
    assert snap["budgetBytes"] == budget * 100
    assert snap["reservedBytes"] == 0  # idle: nothing dispatched
    assert snap["tableBytes"] > 0
    assert snap["headroomBytes"] == (snap["budgetBytes"]
                                     - snap["reservedBytes"]
                                     - snap["resultCacheBytes"]
                                     - snap["tableBytes"])
    c.sql("SELECT k, SUM(v) AS s FROM t GROUP BY k", return_futures=False)
    snap2 = c.ledger.snapshot()
    assert snap2["resultCacheBytes"] == c._result_cache.stats.bytes


def test_ledger_gauges_match_scheduler_inflight_gauge():
    """Acceptance: the ledger's reserved gauge reads the SAME counter the
    scheduler's ``serving.scheduler.inflight_bytes`` gauge publishes."""
    from dask_sql_tpu.serving.runtime import ServingRuntime
    from dask_sql_tpu.serving.scheduler import QueryCost

    c = _ctx()
    runtime = ServingRuntime(workers=2, metrics=c.metrics,
                             scheduler_budget_bytes=1 << 20)
    c.serving = runtime
    try:
        release = threading.Event()

        def hold(ticket):
            release.wait(10.0)
            return None

        runtime.submit(hold, cost=QueryCost(bytes_lo=12345))
        deadline = time.time() + 5.0
        while time.time() < deadline:
            if c.ledger.reserved_bytes() == 12345:
                break
            time.sleep(0.005)
        snap = c.ledger.publish(c.metrics)
        gauges = c.metrics.snapshot()["gauges"]
        assert snap["reservedBytes"] == 12345
        assert gauges["serving.ledger.reserved_bytes"] == 12345
        assert gauges["serving.scheduler.inflight_bytes"] == 12345
        release.set()
    finally:
        release.set()
        runtime.shutdown(wait=True)
    assert c.ledger.reserved_bytes() == 0  # back to idle after release


def test_prometheus_golden_ledger_gauges(tmp_path):
    """Golden exposition of the ledger gauge block (satellite: golden-file
    update for the new gauges)."""
    c = _ctx(rows=32, name="ldg")
    config_module.config.update(
        {"serving.admission.max_estimated_bytes": 1 << 20})
    from dask_sql_tpu.serving.cache import table_nbytes

    t_bytes = sum(table_nbytes(dc.table)
                  for dc in c.schema["root"].tables.values())
    reg = MetricsRegistry()
    c.ledger.publish(reg)
    text = render_prometheus(reg.snapshot())
    assert text == (
        "# TYPE dsql_query_cache_hit_rate gauge\n"
        "dsql_query_cache_hit_rate 0\n"
        "# TYPE dsql_serving_ledger_budget_bytes gauge\n"
        f"dsql_serving_ledger_budget_bytes {1 << 20}\n"
        "# TYPE dsql_serving_ledger_cache_bytes gauge\n"
        "dsql_serving_ledger_cache_bytes 0\n"
        "# TYPE dsql_serving_ledger_headroom_bytes gauge\n"
        f"dsql_serving_ledger_headroom_bytes {(1 << 20) - t_bytes}\n"
        "# TYPE dsql_serving_ledger_inflight_measured_bytes gauge\n"
        "dsql_serving_ledger_inflight_measured_bytes 0\n"
        "# TYPE dsql_serving_ledger_materialized_bytes gauge\n"
        "dsql_serving_ledger_materialized_bytes 0\n"
        "# TYPE dsql_serving_ledger_model_bytes gauge\n"
        "dsql_serving_ledger_model_bytes 0\n"
        "# TYPE dsql_serving_ledger_reserve_drift_bytes gauge\n"
        "dsql_serving_ledger_reserve_drift_bytes 0\n"
        "# TYPE dsql_serving_ledger_reserved_bytes gauge\n"
        "dsql_serving_ledger_reserved_bytes 0\n"
        "# TYPE dsql_serving_ledger_table_bytes gauge\n"
        f"dsql_serving_ledger_table_bytes {t_bytes}\n"
    )


# ------------------------------------------------- cross-query causality
def test_batch_member_and_leader_traces_carry_flow_links():
    from dask_sql_tpu.families.batcher import FamilyBatcher

    batcher = FamilyBatcher(max_queries=4, window_ms=500.0,
                            busy=lambda: True)
    traces = [QueryTrace(sql="q0"), QueryTrace(sql="q1")]
    barrier = threading.Barrier(2)
    outs = [None, None]

    def worker(i):
        def solo():
            return [(i,)]

        def batched(members):
            return [[m] for m in members]

        with activate(traces[i]):
            barrier.wait(5.0)
            outs[i] = batcher.run("key", (i,), solo, batched)

    threads = [threading.Thread(target=worker, args=(i,)) for i in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10.0)
    assert outs[0] is not None and outs[1] is not None
    all_spans = {tr: [s.name for s in tr.spans] for tr in traces}
    leader = next(tr for tr in traces
                  if "batch_launch" in all_spans[tr])
    member = next(tr for tr in traces if tr is not leader)
    assert "batch_join" in all_spans[member]
    join = next(s for s in member.spans if s.name == "batch_join")
    launch = next(s for s in leader.spans if s.name == "batch_launch")
    # the member's flow OUT terminates at the leader's launch flow IN
    assert join.attrs["flow_out"] == launch.attrs["flow_in"]
    # traces are cross-linked so /v1/trace merges both endpoints
    assert leader.qid in member.links
    assert member.qid in leader.links
    merged = merge_chrome_traces([member, leader])
    flows = [e for e in merged["traceEvents"]
             if e.get("cat") == "dsql.flow"]
    starts = {e["id"] for e in flows if e["ph"] == "s"}
    finishes = {e["id"] for e in flows if e["ph"] == "f"}
    assert starts & finishes  # arrow has both endpoints
    # member and leader render as distinct processes in the merged export
    assert {e["pid"] for e in merged["traceEvents"]} == {1, 2}
    # flight recorder saw both roles
    names = {e["event"] for e in flight.RECORDER.events()}
    assert {"batch.lead", "batch.member"} <= names


def test_flow_events_in_single_trace_chrome_export():
    tr = QueryTrace(sql="x")
    tr.event("batch_join", flow_out="g:1")
    out = tr.to_chrome_trace()
    flows = [e for e in out["traceEvents"] if e.get("cat") == "dsql.flow"]
    assert len(flows) == 1 and flows[0]["ph"] == "s"


# -------------------------------------------------------- flight recorder
def test_flight_ring_is_bounded_and_filterable():
    rec = flight.FlightRecorder(capacity=32)
    for i in range(100):
        rec.record("query.admit", qid=f"q{i}")
    assert len(rec) == 32
    assert rec.recorded == 100
    newest = rec.events(limit=5)
    assert [e["qid"] for e in newest] == [f"q{i}" for i in range(95, 100)]
    assert rec.events(qid="q99")[0]["qid"] == "q99"
    assert rec.events(name="query.shed") == []


def test_flight_vocabulary_oracle():
    assert flight.is_registered_event("query.admit")
    assert flight.is_registered_event("breaker.trip")
    assert not flight.is_registered_event("query.admitt")
    assert not flight.is_registered_event("made.up")


def test_flight_auto_flush_on_query_failure(tmp_path):
    dump = tmp_path / "flight.jsonl"
    c = _ctx()
    config_module.config.update({
        "observability.flight.dump_path": str(dump),
        "resilience.ladder.enabled": False,
    })
    from dask_sql_tpu.resilience import faults

    faults.reset()
    with pytest.raises(Exception):
        c.sql("SELECT a FROM lt", return_futures=False,
              config_options={"resilience.inject": "execute:once"})
    faults.reset()
    lines = dump.read_text().strip().splitlines()
    assert len(lines) == 1
    record = json.loads(lines[0])
    assert record["error"]
    assert record["qid"]
    assert any(e["event"] == "query.admit" or e["event"] == "query.fail"
               for e in record["events"]) or record["events"] == []
    # the live table shows the failure too
    entry = c.live_queries.get(record["qid"])
    assert entry is not None and entry.state == "failed"
    assert c.metrics.counter("observability.flight.dumps") == 1


def test_dsql501_flags_unregistered_flight_event():
    from dask_sql_tpu.analysis.selflint import lint_source

    bad = "def f(flight, qid):\n    flight.record('bogus.event', qid=qid)\n"
    findings = lint_source(bad, "x.py")
    assert any(f.rule == "DSQL501" for f in findings)
    good = "def f(flight, qid):\n    flight.record('query.admit', qid=qid)\n"
    assert not [f for f in lint_source(good, "x.py")
                if f.rule == "DSQL501"]
    suppressed = ("def f(flight, qid):\n"
                  "    flight.record('bogus.event')"
                  "  # dsql: allow-flight-event\n")
    assert not [f for f in lint_source(suppressed, "x.py")
                if f.rule == "DSQL501"]


def test_dsql401_now_covers_gauges():
    from dask_sql_tpu.analysis.selflint import lint_source

    bad = "def f(metrics):\n    metrics.gauge('bogus.gauge', 1.0)\n"
    assert any(f.rule == "DSQL401" for f in lint_source(bad, "x.py"))
    good = ("def f(metrics):\n"
            "    metrics.gauge('serving.ledger.reserved_bytes', 1.0)\n")
    assert not [f for f in lint_source(good, "x.py")
                if f.rule == "DSQL401"]


def test_breaker_restore_detected_on_half_open_success():
    from dask_sql_tpu.resilience.retry import CircuitBreaker

    b = CircuitBreaker(threshold=1, cooldown_s=0.0)
    key = ("fp", "compiled_aggregate")
    assert b.record_failure(key)  # trips
    assert b.is_open(key)
    assert b.record_success(key) is True  # restore of an OPEN circuit
    b.record_failure(("fp2", "r"))  # sub-threshold? threshold=1 -> open
    assert b.record_success(("fp3", "r")) is False  # never failed


# ------------------------------------------------- queue-wait attribution
def test_scheduler_stamps_queue_wait_cause():
    from dask_sql_tpu.serving.admission import QueryTicket
    from dask_sql_tpu.serving.scheduler import PackingScheduler, QueryCost

    sched = PackingScheduler(budget_bytes=100)
    t1, t2 = QueryTicket("big"), QueryTicket("small")
    sched.push_locked(t1, lambda: None, None, QueryCost(bytes_lo=80))
    sched.push_locked(t2, lambda: None, None, QueryCost(bytes_lo=50))
    got = sched.pop_locked(batch_ok=True)
    assert got[0] is t1
    assert sched.pop_locked(batch_ok=True) is None  # byte-blocked
    sched.release_locked(t1)
    got2 = sched.pop_locked(batch_ok=True)
    assert got2[0] is t2
    assert t2.queue_reason == "byte_blocked"


# ------------------------------------ store bounds under concurrent load
def test_trace_store_bounds_with_eviction_racing_readers():
    store = TraceStore(keep=8)
    stop = threading.Event()
    failures = []

    def writer(tid):
        try:
            for i in range(300):
                tr = QueryTrace(sql=f"q{tid}-{i}")
                store.put(tr.qid, tr)
        except BaseException as exc:  # noqa: BLE001 - surfaced below
            failures.append(exc)

    def reader():
        try:
            while not stop.is_set():
                store.get("nope")
                assert len(store) <= 8
        except BaseException as exc:  # noqa: BLE001 - surfaced below
            failures.append(exc)

    writers = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
    readers = [threading.Thread(target=reader) for _ in range(4)]
    for t in writers + readers:
        t.start()
    for t in writers:
        t.join(30.0)
    stop.set()
    for t in readers:
        t.join(10.0)
    assert not failures
    assert len(store) <= 8


def test_profile_store_bounds_with_eviction_racing_readers():
    store = ProfileStore(window=4, keep=6)
    stop = threading.Event()
    failures = []

    def writer(tid):
        try:
            for i in range(200):
                fp = f"fp-{tid}-{i % 10}"
                store.record_exec(fp, sql=f"SELECT {i}", exec_ms=float(i),
                                  result_bytes=i)
                store.record_compile(fp, "compiled_aggregate", float(i))
        except BaseException as exc:  # noqa: BLE001 - surfaced below
            failures.append(exc)

    def reader():
        try:
            while not stop.is_set():
                store.rows()
                store.snapshot()
                assert len(store) <= 6
        except BaseException as exc:  # noqa: BLE001 - surfaced below
            failures.append(exc)

    writers = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
    readers = [threading.Thread(target=reader) for _ in range(4)]
    for t in writers + readers:
        t.start()
    for t in writers:
        t.join(30.0)
    stop.set()
    for t in readers:
        t.join(10.0)
    assert not failures
    assert len(store) <= 6


# ----------------------------------------------------------- wire surface
@pytest.fixture()
def live_server():
    from dask_sql_tpu.server.app import PrestoServer

    c = _ctx(rows=256, name="wt")
    srv = PrestoServer(context=c, host="127.0.0.1", port=0)
    srv.start_background()
    yield c, srv
    srv.shutdown()


def _wire(base, path, method="GET", body=b""):
    req = urllib.request.Request(base + path, method=method,
                                 data=body if method == "POST" else None)
    return json.load(urllib.request.urlopen(req))


def test_wire_queries_endpoint_and_cancel(live_server):
    c, srv = live_server
    base = f"http://127.0.0.1:{srv.port}"
    out = _wire(base, "/v1/statement", "POST",
                b"SELECT SUM(a) AS s FROM wt")
    qid = out["id"]
    deadline = time.time() + 10.0
    while time.time() < deadline:
        st = _wire(base, f"/v1/statement/{qid}")
        if "data" in st or "error" in st:
            break
        time.sleep(0.01)
    snap = _wire(base, "/v1/queries")
    entry = next(e for e in snap["queries"] if e["qid"] == qid)
    assert entry["state"] == "done"
    assert entry["rung"]
    assert "ledger" in snap and "reservedBytes" in snap["ledger"]
    one = _wire(base, f"/v1/queries/{qid}")
    assert one["qid"] == qid
    # cancel of a terminal query is a 404, not a crash
    with pytest.raises(urllib.error.HTTPError):
        _wire(base, f"/v1/queries/{qid}/cancel", "POST")
    # the debug-events dump is live and filterable
    ev = _wire(base, "/v1/debug/events?name=query.admit")
    assert any(e.get("qid") == qid for e in ev["events"])


def test_wire_metrics_includes_ledger_gauges(live_server):
    c, srv = live_server
    base = f"http://127.0.0.1:{srv.port}"
    body = urllib.request.urlopen(
        base + "/v1/metrics?format=prometheus").read().decode()
    assert "dsql_serving_ledger_table_bytes" in body
    assert "dsql_serving_ledger_reserved_bytes 0" in body
    snap = _wire(base, "/v1/metrics")
    assert "ledger" in snap
