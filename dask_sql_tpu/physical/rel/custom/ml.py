"""ML statement converters: CREATE MODEL / PREDICT / EXPERIMENT / EXPORT.

Role parity (reference physical/rel/custom/): create_model.py:23 (WITH
options: model_class, target_column, wrap_predict, wrap_fit, fit_kwargs),
predict_model.py:15 (PREDICT(MODEL m, <select>) appends a `target` column),
create_experiment.py:22 (GridSearchCV-style tuning), export_model.py:15
(pickle/joblib/mlflow/onnx), describe_model.py, drop_model.py.
"""
from __future__ import annotations

import numpy as np

from ....columnar.column import Column
from ....columnar.table import Table
from ....planner import plan as p
from ....resilience.errors import (
    ModelError,
    ModelNotFoundError,
    QueryError,
    ResourceExhaustedError,
    classify,
)
from ..base import BaseRelPlugin, unique_names
from ...executor import Executor

_EMPTY = Table({}, 0)


def _model_boundary(stage: str, fn):
    """Run one model-layer step under the structured error taxonomy: a
    failing fit/predict/class-resolution leaves here as a `ModelError`
    (USER_ERROR on the Presto wire) instead of a raw traceback that
    bypasses the QueryError code mapping."""
    try:
        return fn()
    except QueryError:
        raise
    except (KeyboardInterrupt, SystemExit):
        raise
    except BaseException as exc:
        # resource exhaustion (MemoryError on the pulled-to-host frame, an
        # XLA RESOURCE_EXHAUSTED from a wrapped jax model) keeps its
        # taxonomy class: the host tier is itself a degradation target and
        # USER_ERROR would tell the client their statement is wrong
        wrapped = classify(exc)
        if isinstance(wrapped, ResourceExhaustedError):
            raise wrapped
        raise ModelError(
            f"{stage} failed: {type(exc).__name__}: {exc}") from exc


def _split_xy(df, target_column):
    if target_column:
        X = df.drop(columns=[target_column])
        y = df[target_column]
    else:
        X, y = df, None
    return X, y


@Executor.add_plugin_class
class CreateModelPlugin(BaseRelPlugin):
    class_name = "CreateModelNode"

    def convert(self, rel: p.CreateModelNode, executor) -> Table:
        from ....ml.ml_classes import get_model_class
        from ....ml.wrappers import Incremental, ParallelPostFit

        ctx = executor.context
        schema_name, name = ctx._table_schema_name(rel.name)
        if name in ctx.schema[schema_name].models:
            if rel.if_not_exists:
                return _EMPTY
            if not rel.or_replace:
                raise ModelError(
                    f"A model with the name {name} is already present.")
        kwargs = dict(rel.kwargs)
        model_class = kwargs.pop("model_class", None)
        if model_class is None:
            raise ModelError("CREATE MODEL requires a model_class parameter")
        if kwargs.pop("experiment_class", None) is not None:
            # historically popped and silently dropped — surface the
            # misdirected option instead of training something else
            raise ModelError(
                "experiment_class is a CREATE EXPERIMENT option; CREATE "
                "MODEL trains model_class directly — use CREATE "
                "EXPERIMENT for tuned fits")
        target_column = kwargs.pop("target_column", "")
        wrap_predict = _boolish(kwargs.pop("wrap_predict", False))
        wrap_fit = _boolish(kwargs.pop("wrap_fit", False))
        fit_kwargs = kwargs.pop("fit_kwargs", {}) or {}
        backend = kwargs.pop("backend", "tpu")
        kwargs.pop("gpu", None)

        training_table = executor.execute(rel.input)
        df = training_table.to_pandas()
        X, y = _split_xy(df, target_column)

        ModelClass = _model_boundary(
            "model_class resolution",
            lambda: get_model_class(str(model_class), backend=str(backend)))

        def fit():
            model = ModelClass(**kwargs)
            if wrap_fit:
                model = Incremental(model)
            if y is not None:
                model.fit(X.to_numpy(), y.to_numpy(), **fit_kwargs)
            else:
                model.fit(X.to_numpy(), **fit_kwargs)
            return model

        model = _model_boundary(f"CREATE MODEL {name} fit", fit)
        if wrap_predict and not isinstance(model, (ParallelPostFit, Incremental)):
            model = ParallelPostFit(model)
        ctx.register_model(name, model, list(X.columns), schema_name=schema_name)
        return _EMPTY


@Executor.add_plugin_class
class PredictModelPlugin(BaseRelPlugin):
    class_name = "PredictModelNode"

    def convert(self, rel: p.PredictModelNode, executor) -> Table:
        ctx = executor.context
        schema_name, name = ctx._table_schema_name(rel.model_name)
        if name not in ctx.schema[schema_name].models:
            raise ModelNotFoundError(
                f"A model with the name {name} is not present.")
        model, training_columns = ctx.get_model(schema_name, name)
        inp = executor.execute(rel.input)
        df = inp.to_pandas()
        # the host tier: pull to pandas, predict on numpy, re-upload —
        # where PREDICTs land when the fused compiled_predict rung
        # (physical/compiled_predict.py) declines or degrades
        ctx.metrics.inc("inference.predict.host")
        pred = _model_boundary(
            f"PREDICT(MODEL {name})",
            lambda: model.predict(df[training_columns].to_numpy()))
        names = unique_names([f.name for f in rel.schema])
        cols = dict(zip(names[:-1], [inp.columns[c] for c in inp.column_names]))
        cols[names[-1]] = Column.from_numpy(np.asarray(pred))
        return Table(cols, inp.num_rows)


@Executor.add_plugin_class
class DropModelPlugin(BaseRelPlugin):
    class_name = "DropModelNode"

    def convert(self, rel: p.DropModelNode, executor) -> Table:
        ctx = executor.context
        schema_name, name = ctx._table_schema_name(rel.name)
        if name not in ctx.schema[schema_name].models:
            if rel.if_exists:
                return _EMPTY
            raise ModelNotFoundError(
                f"A model with the name {name} is not present.")
        del ctx.schema[schema_name].models[name]
        from ....inference import invalidate

        invalidate(ctx, schema_name, name)  # ledger stops charging params
        return _EMPTY


@Executor.add_plugin_class
class DescribeModelPlugin(BaseRelPlugin):
    class_name = "DescribeModelNode"

    def convert(self, rel: p.DescribeModelNode, executor) -> Table:
        ctx = executor.context
        schema_name, name = ctx._table_schema_name(rel.name)
        if name not in ctx.schema[schema_name].models:
            raise ModelNotFoundError(
                f"A model with the name {name} is not present.")
        model, training_columns = ctx.get_model(schema_name, name)
        params = model.get_params() if hasattr(model, "get_params") else {}
        params["training_columns"] = training_columns
        # the lowering verdict (inference/): does this model serve on the
        # compiled tier, how many device param bytes, what shape
        from ....inference import lowering_verdict

        verdict = lowering_verdict(ctx, schema_name, name)
        params["lowering.tier"] = verdict["tier"]
        params["lowering.param_bytes"] = verdict["param_bytes"]
        params["lowering.shape"] = verdict["shape"]
        keys = np.array([str(k) for k in params.keys()], dtype=object)
        vals = np.array([str(v) for v in params.values()], dtype=object)
        return Table({"Params": Column.from_numpy(keys),
                      "Value": Column.from_numpy(vals)}, len(keys))


@Executor.add_plugin_class
class ExportModelPlugin(BaseRelPlugin):
    class_name = "ExportModelNode"

    def convert(self, rel: p.ExportModelNode, executor) -> Table:
        ctx = executor.context
        schema_name, name = ctx._table_schema_name(rel.name)
        model, training_columns = ctx.get_model(schema_name, name)
        kwargs = dict(rel.kwargs)
        fmt = str(kwargs.pop("format", "pickle")).lower()
        location = kwargs.pop("location", "tmp.pkl")
        if fmt in ("pickle", "pkl"):
            import pickle

            with open(location, "wb") as f:
                pickle.dump(model, f, **kwargs)
        elif fmt == "joblib":
            import joblib

            joblib.dump(model, location, **kwargs)
        elif fmt == "mlflow":
            try:
                import mlflow
            except ImportError as e:  # pragma: no cover
                raise RuntimeError("mlflow is not installed") from e
            mlflow.sklearn.save_model(model, location, **kwargs)
        elif fmt == "onnx":
            raise ModelError(
                "ONNX export requires skl2onnx, which is not installed here")
        else:
            raise ModelError(f"EXPORT MODEL format {fmt!r} is not supported")
        return _EMPTY


@Executor.add_plugin_class
class CreateExperimentPlugin(BaseRelPlugin):
    class_name = "CreateExperimentNode"

    def convert(self, rel: p.CreateExperimentNode, executor) -> Table:
        from ....ml.ml_classes import get_model_class

        ctx = executor.context
        schema_name, name = ctx._table_schema_name(rel.name)
        if name in ctx.schema[schema_name].experiments:
            if rel.if_not_exists:
                return _EMPTY
            if not rel.or_replace:
                raise RuntimeError(f"An experiment with the name {name} is already present.")
        kwargs = dict(rel.kwargs)
        model_class = kwargs.pop("model_class", None)
        experiment_class = kwargs.pop("experiment_class", "sklearn.model_selection.GridSearchCV")
        tune_parameters = kwargs.pop("tune_parameters", {}) or {}
        target_column = kwargs.pop("target_column", "")
        automl_class = kwargs.pop("automl_class", None)
        experiment_kwargs = kwargs.pop("experiment_kwargs", {}) or {}
        kwargs.pop("gpu", None)

        training_table = executor.execute(rel.input)
        df = training_table.to_pandas()
        X, y = _split_xy(df, target_column)

        if automl_class:
            raise NotImplementedError(
                "AutoML (TPOT-style) experiments need the automl package installed")
        if model_class is None:
            raise ModelError("CREATE EXPERIMENT requires a model_class")
        ModelClass = get_model_class(str(model_class), backend="cpu")
        base = ModelClass()
        ExperimentClass = get_model_class(str(experiment_class), backend="cpu")
        tuner = ExperimentClass(base, {k: list(v) if isinstance(v, (list, tuple)) else [v]
                                       for k, v in tune_parameters.items()},
                                **experiment_kwargs)
        tuner.fit(X.to_numpy(), y.to_numpy() if y is not None else None)
        import pandas as pd

        results = pd.DataFrame(tuner.cv_results_)
        ctx.schema[schema_name].experiments[name] = results
        ctx.register_model(name, tuner.best_estimator_, list(X.columns),
                           schema_name=schema_name)
        out = Table.from_pandas(results.astype(str))
        return out


def _boolish(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).lower() in ("true", "1", "yes")
