"""Shared plumbing for the SPMD compiled rungs.

The single-chip compiled pipelines (physical/compiled*.py) trace a function
of ``(datas, valids, row_valid, params)`` where ``valids`` entries and
``row_valid`` may be ``None``.  `shard_map` wants a concrete pytree of
arrays with one PartitionSpec per leaf, so this module packs the optional
arguments into flag-described tuples: column data and the row mask shard
row-block over the mesh axis, runtime parameters replicate.

The wrap is built ONCE per pipeline (the flags are static properties of the
bound table), and the returned jitted callable is what `timed_jit_call`
watches for fresh XLA compiles — the spmd rungs get the same compile-span /
compile-histogram accounting as the single-chip rungs.
"""
from __future__ import annotations

import logging
from typing import Callable, Sequence, Tuple

import jax

try:
    from jax import shard_map
except ImportError:  # pre-0.4.x top-level export: experimental namespace
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import AXIS

logger = logging.getLogger(__name__)


def _mode(config, key: str, default="auto") -> str:
    return str(config.get(key, default)).lower()


def spmd_enabled(config) -> bool:
    """Master switch for the sharded compiled rungs (``parallel.spmd``)."""
    return _mode(config, "parallel.spmd") not in ("off", "false", "0", "none")


def rung_enabled(config, rung: str) -> bool:
    """Per-rung toggle under the master switch, e.g.
    ``parallel.spmd.select`` for ``spmd_select``."""
    if not spmd_enabled(config):
        return False
    short = rung[len("spmd_"):] if rung.startswith("spmd_") else rung
    v = config.get(f"parallel.spmd.{short}", True)
    return str(v).lower() not in ("off", "false", "0", "none")


def mesh_of_sharded_table(table):
    """The mesh a table's buffers are row-sharded over, or None when the
    table is not mesh-sharded (or the mesh has a single device)."""
    from ..parallel.dist_plan import mesh_for_table

    mesh = mesh_for_table(table)
    if mesh is None or mesh.devices.size < 2:
        return None
    return mesh


def mesh_key(mesh) -> Tuple[int, ...]:
    """Stable cache-key component for a mesh (device ids in mesh order)."""
    return tuple(int(d.id) for d in mesh.devices.flat)


def resolve_sharded_scan(context, node):
    """(table, mesh) when a TableScan reads a registered, device-resident
    (non-lazy), mesh-sharded table; None otherwise.  THE sharding-detection
    rule, shared by the estimator's per-device budgeting and the EXPLAIN
    LINT advisory so they can never disagree with the rungs.  Never touches
    lazy parquet containers (no accidental loads)."""
    if context is None:
        return None
    schema = getattr(context, "schema", {}).get(node.schema_name)
    dc = schema.tables.get(node.table_name) if schema else None
    if dc is None:
        return None
    from ..datacontainer import LazyParquetContainer

    if isinstance(dc, LazyParquetContainer):
        return None
    table = getattr(dc, "table", None)
    if table is None:
        return None
    mesh = mesh_of_sharded_table(table)
    if mesh is None:
        return None
    return table, mesh


class ColumnSpmdWrap:
    """shard_map wrapper around a traced pipeline callable.

    ``fn_raw(datas, valids, row_valid, params)`` is the raw (unjitted)
    pipeline function; ``valid_present[i]`` says whether column i carries a
    validity mask and ``has_row_valid`` whether the table is padded — the
    ``None`` slots are re-inserted inside the mapped function so the traced
    body is IDENTICAL to the single-chip trace, just over per-shard rows.

    ``out_specs`` follows shard_map semantics: ``P(None, ...)`` outputs are
    device-invariant (everything derived from psum/pmin/pmax partials),
    ``P(AXIS, ...)``/``P(..., AXIS)`` outputs stay sharded.
    """

    def __init__(self, fn_raw: Callable, mesh,
                 valid_present: Sequence[bool], has_row_valid: bool,
                 n_params: int, out_specs, check_rep: bool = True):
        self.mesh = mesh
        self.valid_present = tuple(bool(v) for v in valid_present)
        self.has_row_valid = bool(has_row_valid)
        n_cols = len(self.valid_present)
        n_valid = sum(self.valid_present)

        def packed_fn(datas, valids_p, row_valid_t, params):
            valids = []
            i = 0
            for present in self.valid_present:
                if present:
                    valids.append(valids_p[i])
                    i += 1
                else:
                    valids.append(None)
            rv = row_valid_t[0] if row_valid_t else None
            return fn_raw(tuple(datas), tuple(valids), rv, tuple(params))

        in_specs = (
            (P(AXIS),) * n_cols,
            (P(AXIS),) * n_valid,
            (P(AXIS),) * (1 if self.has_row_valid else 0),
            (P(),) * n_params,
        )
        self.mapped = shard_map(packed_fn, mesh=mesh, in_specs=in_specs,
                                out_specs=out_specs,
                                check_rep=check_rep)
        self.jitted = jax.jit(self.mapped)

    def pack_args(self, datas, valids, row_valid, params) -> Tuple:
        """(datas, valids, row_valid, params) -> the 4 packed positional
        arguments of the mapped/jitted callable."""
        valids_p = tuple(v for v, present in zip(valids, self.valid_present)
                         if present)
        rv = (row_valid,) if self.has_row_valid else ()
        return (tuple(datas), valids_p, rv, tuple(params))
