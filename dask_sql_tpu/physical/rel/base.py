"""Base plugin for relational converters.

Role parity: reference BaseRelPlugin (physical/rel/base.py there):
`assert_inputs` recursive child conversion (base.py:67-86), schema/dtype
fix-up helpers (fix_column_to_row_type base.py:32, fix_dtype_to_row_type
base.py:89).
"""
from __future__ import annotations

from typing import List

from ...columnar.table import Table
from ...planner.expressions import Schema
from ...planner.plan import LogicalPlan


class BaseRelPlugin:
    class_name: str = ""

    def convert(self, rel: LogicalPlan, executor) -> Table:
        raise NotImplementedError

    @staticmethod
    def assert_inputs(rel: LogicalPlan, n: int, executor) -> List[Table]:
        inputs = rel.inputs()
        assert len(inputs) == n, f"{rel.node_type} expects {n} inputs"
        return [executor.execute(i) for i in inputs]

    @staticmethod
    def fix_column_to_row_type(table: Table, schema: Schema) -> Table:
        """Rename positional columns to the plan's field names (made unique)."""
        names = unique_names([f.name for f in schema])
        cols = {}
        for new, old in zip(names, table.column_names):
            cols[new] = table.columns[old]
        return Table(cols, table.num_rows)

    @staticmethod
    def fix_dtype_to_row_type(table: Table, schema: Schema) -> Table:
        cols = {}
        for name, f in zip(table.column_names, schema):
            col = table.columns[name]
            if col.sql_type != f.sql_type:
                col = col.cast(f.sql_type)
            cols[name] = col
        return Table(cols, table.num_rows)


def unique_names(names: List[str]) -> List[str]:
    """Disambiguate duplicates with __N suffixes, collision-proof against
    inputs that already carry a suffix (a 3-way self-join's second 'g' must
    not collide with an existing 'g__1' — Table columns are a dict, so a
    collision silently DROPS a column)."""
    seen = set()
    counts: dict = {}
    out = []
    for n in names:
        if n not in seen:
            seen.add(n)
            out.append(n)
            continue
        i = counts.get(n, 0) + 1
        cand = f"{n}__{i}"
        while cand in seen:
            i += 1
            cand = f"{n}__{i}"
        counts[n] = i
        seen.add(cand)
        out.append(cand)
    return out
