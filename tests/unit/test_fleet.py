"""Fault-tolerant replica fleet (ISSUE 18): router with health-gated
cost-aware routing and failover, epoch-fenced write fan-out, warm-standby
promotion over the checkpoint transport, graceful drain (replica and HTTP
server), the bounded shutdown drain, per-process flight-dump paths, and
SHOW REPLICAS.

The chaos-level composition proof (replica-kill campaign) lives in
tests/unit/test_chaos.py::test_fleet_campaign_* and `bench.py --fleet`;
this module covers the mechanisms one at a time.
"""
import json
import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu import config as config_module
from dask_sql_tpu.fleet import (
    DEAD,
    DRAINING,
    READY,
    STANDBY,
    Replica,
    build_fleet,
)
from dask_sql_tpu.observability import flight
from dask_sql_tpu.resilience.errors import ReplicaFailedError, ShutdownError

pytestmark = pytest.mark.fleet


@pytest.fixture
def config_keys():
    """Update GLOBAL config keys for the test, restoring originals after
    (worker/warm-up threads read base config, not this thread's overlay)."""
    cfg = config_module.config
    saved = {}

    def apply(options):
        for k, v in options.items():
            saved.setdefault(k, cfg.get(k))
        cfg.update(options)

    yield apply
    cfg.update(saved)


def _ctx():
    c = Context()
    c.create_table("t", pd.DataFrame({
        "x": np.arange(8, dtype=np.float64),
        "g": np.arange(8, dtype=np.int64) % 2,
    }))
    return c


def _slow_ctx(sleep_s=0.05, rows=4):
    c = Context()
    c.create_table("sleepy", pd.DataFrame({
        "a": np.arange(rows, dtype=np.int64)}))

    def slowid(row):
        time.sleep(sleep_s)
        return int(row["a"])

    c.register_function(slowid, "slowid", [("a", np.int64)], np.int64,
                        row_udf=True)
    return c


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------
def test_router_routes_and_answers():
    router, members, _ = build_fleet(_ctx, replicas=2)
    try:
        out = router.execute("SELECT SUM(x) AS s FROM t", qid="r1")
        assert int(out["s"][0]) == 28
        rows = router.rows()
        assert [r[0] for r in rows] == ["replica-0", "replica-1"]
        assert sum(int(r[4]) for r in rows) == 1  # routed exactly once
    finally:
        router.shutdown()


def test_router_health_gates_and_orders_by_headroom():
    router, members, _ = build_fleet(_ctx, replicas=2)
    try:
        # health payload carries the routing facts (satellite 1's shape)
        h = members[0].health()
        assert h["status"] == "ready"
        assert h["band"] in ("green", "yellow", "red", "critical")
        assert "headroomBytes" in h
        # a non-READY replica is not routable and never picked
        members[0].drain(wait=True)
        assert not members[0].routable
        out = router.execute("SELECT COUNT(*) AS n FROM t", qid="r2")
        assert int(out["n"][0]) == 8
        assert int(dict((r[0], r[4]) for r in router.rows())["replica-1"]) == 1
    finally:
        router.shutdown()


def test_router_spills_to_peer_on_queue_full(config_keys):
    # replica queues bounded to 1 with a single worker: a burst must spill
    # to the peer instead of surfacing 429s while a peer has room
    config_keys({"serving.workers": 1,
                 "serving.queue.interactive": 1,
                 "serving.queue.batch": 1})
    router, members, _ = build_fleet(_slow_ctx, replicas=2)
    try:
        results, errors = [], []

        def client(i):
            try:
                results.append(router.execute(
                    "SELECT SUM(slowid(a)) AS s FROM sleepy",
                    qid=f"spill-{i}"))
            except Exception as e:  # noqa: BLE001 — tallied below
                errors.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errors, errors
        assert all(int(r["s"][0]) == 6 for r in results)
        routed = {r[0]: int(r[4]) for r in router.rows()}
        assert sum(routed.values()) >= 3
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# failover
# ---------------------------------------------------------------------------
def test_failover_reroutes_killed_replica_midquery():
    router, members, _ = build_fleet(lambda: _slow_ctx(sleep_s=0.1),
                                     replicas=2)
    try:
        box = {}

        def client():
            box["out"] = router.execute(
                "SELECT SUM(slowid(a)) AS s FROM sleepy", qid="kill-mid")

        th = threading.Thread(target=client)
        th.start()
        time.sleep(0.15)  # the query is mid-flight on replica-0
        router.kill("replica-0")
        th.join(60)
        assert int(box["out"]["s"][0]) == 6  # answered by the survivor
        evs = flight.RECORDER.events(name="fleet.failover", qid="kill-mid")
        assert evs, "failover must be recorded in the flight ring"
        assert members[0].state == DEAD
    finally:
        router.shutdown()


def test_replica_failed_error_is_retryable_taxonomy():
    e = ReplicaFailedError("replica died", query_id="q1")
    assert e.retryable
    assert e.code == "REPLICA_FAILED"


def test_failover_exhaustion_surfaces_last_error():
    router, members, _ = build_fleet(_ctx, replicas=2)
    try:
        for m in members:
            m.kill()
        with pytest.raises(ReplicaFailedError):
            router.execute("SELECT 1 AS one", qid="dead-fleet")
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# write fan-out: epoch-fenced exactly-once
# ---------------------------------------------------------------------------
def test_write_fans_out_and_fences_duplicates():
    router, members, _ = build_fleet(_ctx, replicas=2)
    try:
        ins = "INSERT INTO t SELECT x + 100, g FROM t WHERE x < 1"
        router.execute(ins, qid="w1")
        for m in members:
            out = m.context.sql("SELECT COUNT(*) AS n FROM t",
                                return_futures=False)
            assert int(out["n"][0]) == 9
            assert m.context.table_epoch("root", "t") == 2
        # a retry under the SAME qid is the same sequenced write: fenced
        router.execute(ins, qid="w1")
        for m in members:
            out = m.context.sql("SELECT COUNT(*) AS n FROM t",
                                return_futures=False)
            assert int(out["n"][0]) == 9
        # an IDENTICAL statement under a distinct qid is an intentional
        # second write — its own sequence slot, applied again everywhere
        router.execute(ins, qid="w1-again")
        for m in members:
            out = m.context.sql("SELECT COUNT(*) AS n FROM t",
                                return_futures=False)
            assert int(out["n"][0]) == 10
            assert m.context.table_epoch("root", "t") == 3
        # a textually distinct write is a new sequence slot too
        router.execute("INSERT INTO t SELECT x + 200, g FROM t WHERE x < 1",
                       qid="w2")
        for m in members:
            out = m.context.sql("SELECT COUNT(*) AS n FROM t",
                                return_futures=False)
            assert int(out["n"][0]) == 11
            assert m.context.table_epoch("root", "t") == 4
    finally:
        router.shutdown()


def test_write_bind_error_never_enters_the_log():
    # poison-pill guard, front door: a statement that cannot bind is
    # rejected BEFORE sequencing — the log stays empty and later writes
    # are not wedged behind a permanently failing entry
    from dask_sql_tpu.resilience.errors import QueryError

    router, members, _ = build_fleet(_ctx, replicas=2)
    try:
        with pytest.raises(Exception) as ei:
            router.execute("INSERT INTO t SELECT nosuch FROM t", qid="bad1")
        assert isinstance(ei.value, QueryError)
        assert not ei.value.retryable
        with pytest.raises(Exception):
            router.execute("INSERT INTO nosuchtable SELECT x FROM t",
                           qid="bad2")
        assert router.snapshot()["writeLog"] in ({}, {"root.t": 0})
        # the log was never poisoned: a valid write still lands everywhere
        router.execute("INSERT INTO t SELECT x + 100, g FROM t WHERE x < 1",
                       qid="good1")
        for m in members:
            out = m.context.sql("SELECT COUNT(*) AS n FROM t",
                                return_futures=False)
            assert int(out["n"][0]) == 9
            assert m.context.table_epoch("root", "t") == 2
    finally:
        router.shutdown()


def test_write_apply_failure_tombstones_instead_of_wedging():
    # poison-pill guard, back door: a statement that binds but fails at
    # apply (incompatible column set surfaces only at the append) is
    # tombstoned — the client gets the structured error once, and every
    # subsequent write proceeds past the slot on all replicas
    from dask_sql_tpu.resilience.errors import QueryError

    router, members, _ = build_fleet(_ctx, replicas=2)
    try:
        with pytest.raises(Exception) as ei:
            router.execute("INSERT INTO t SELECT x FROM t WHERE x < 1",
                           qid="poison")
        assert isinstance(ei.value, QueryError)
        assert not ei.value.retryable
        # the poisoned slot advanced the fence on every replica (noop)
        for m in members:
            assert m.context.table_epoch("root", "t") == 2
        # later writes are NOT wedged behind the poisoned entry
        router.execute("INSERT INTO t SELECT x + 100, g FROM t WHERE x < 1",
                       qid="after-poison")
        for m in members:
            out = m.context.sql("SELECT COUNT(*) AS n FROM t",
                                return_futures=False)
            assert int(out["n"][0]) == 9
            assert m.context.table_epoch("root", "t") == 3
        # a retry of the poisoned qid dedupes to the tombstone: no effect
        with_retry = router.execute(
            "INSERT INTO t SELECT x FROM t WHERE x < 1", qid="poison")
        assert with_retry is None
        for m in members:
            out = m.context.sql("SELECT COUNT(*) AS n FROM t",
                                return_futures=False)
            assert int(out["n"][0]) == 9
    finally:
        router.shutdown()


def test_classification_is_parser_backed_not_regex():
    from dask_sql_tpu.resilience.errors import UnroutableStatementError

    router, members, _ = build_fleet(_ctx, replicas=2)
    try:
        # a leading comment defeated the old regex and would have routed
        # this INSERT to a single replica, diverging the fleet
        router.execute("-- append\nINSERT INTO t "
                       "SELECT x + 100, g FROM t WHERE x < 1", qid="c1")
        for m in members:
            out = m.context.sql("SELECT COUNT(*) AS n FROM t",
                                return_futures=False)
            assert int(out["n"][0]) == 9
            assert m.context.table_epoch("root", "t") == 2
        # non-INSERT mutations are rejected up front with a structured
        # user error instead of executing on one replica
        for sql in ("CREATE TABLE u AS (SELECT x FROM t)",
                    "DROP TABLE t",
                    "ALTER TABLE t RENAME TO t2"):
            with pytest.raises(UnroutableStatementError) as ei:
                router.execute(sql, qid=f"ddl-{hash(sql) & 0xffff}")
            assert not ei.value.retryable
        # nothing diverged: both replicas still agree on catalog + epoch
        for m in members:
            assert m.context.table_epoch("root", "t") == 2
            assert "t" in m.context.schema["root"].tables
            assert "u" not in m.context.schema["root"].tables
    finally:
        router.shutdown()


def test_failover_deprioritizes_just_failed_replica():
    router, members, _ = build_fleet(_ctx, replicas=2)
    try:
        # a per-query avoid set puts the failed member last
        order = router._candidates(0, avoid=("replica-0",))
        assert [r.name for r in order][-1] == "replica-0"
        # a replica-level failure marks the member suspect: it sorts last
        # for every query until the cooldown expires, even while READY
        router._note_failure(members[0])
        assert members[0].state == READY
        order = router._candidates(0)
        assert [r.name for r in order][-1] == "replica-0"
        # end to end: the next query routes around the suspect member
        out = router.execute("SELECT COUNT(*) AS n FROM t", qid="avoid-0")
        assert int(out["n"][0]) == 8
        routed = {r[0]: int(r[4]) for r in router.rows()}
        assert routed == {"replica-0": 0, "replica-1": 1}
    finally:
        router.shutdown()


def test_write_catches_up_replica_behind_the_fence():
    router, members, _ = build_fleet(_ctx, replicas=2)
    try:
        # replica-1 misses a write (killed), then a new member at the same
        # epoch would be behind; the fan-out's catch-up applies pending
        # writes in sequence order rather than tripping the fence
        router.execute("INSERT INTO t SELECT x + 100, g FROM t WHERE x < 1",
                       qid="wa")
        late = Replica("late", _ctx())
        router.replicas.append(late)
        late.context.fleet_router = router
        router.execute("INSERT INTO t SELECT x + 200, g FROM t WHERE x < 1",
                       qid="wb")
        out = late.context.sql("SELECT COUNT(*) AS n FROM t",
                               return_futures=False)
        # late replica caught up: both writes applied exactly once
        assert int(out["n"][0]) == 10
        assert late.context.table_epoch("root", "t") == 3
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# warm-standby promotion over the checkpoint transport
# ---------------------------------------------------------------------------
def test_standby_promotion_replays_missed_writes(tmp_path):
    router, members, repl = build_fleet(
        _ctx, replicas=2, standby=True, sync_dir=str(tmp_path / "sync"))
    try:
        router.execute("SELECT SUM(x) AS s FROM t", qid="warm-1")
        router.execute("INSERT INTO t SELECT x + 100, g FROM t WHERE x < 1",
                       qid="pre-sync")
        repl.sync()
        # satellite 4: the snapshot manifest carried the table epoch, so
        # the standby KNOWS it has seen exactly one sequenced write
        assert router.standby.context.table_epoch("root", "t") == 2
        router.execute("INSERT INTO t SELECT x + 200, g FROM t WHERE x < 1",
                       qid="post-sync")
        router.kill("replica-0")
        sb = router.find("standby")
        assert sb.state == READY and sb in router.replicas
        assert router.standby is None
        # epoch fencing regression: the promoted standby must serve the
        # POST-append state — the missed write was replayed at promotion,
        # and its epoch advanced past the snapshot's
        out = sb.context.sql("SELECT COUNT(*) AS n FROM t",
                             return_futures=False)
        assert int(out["n"][0]) == 10
        assert sb.context.table_epoch("root", "t") == 3
        # and the fleet answer agrees with the surviving original
        via_router = router.execute("SELECT COUNT(*) AS n FROM t",
                                    qid="after-promote")
        assert int(via_router["n"][0]) == 10
        assert flight.RECORDER.events(name="fleet.promote")
    finally:
        router.shutdown()


def test_standby_not_promoted_when_disabled(config_keys):
    config_keys({"fleet.standby.auto_promote": False})
    router, members, _ = build_fleet(_ctx, replicas=2, standby=True)
    try:
        router.kill("replica-0")
        assert router.standby is not None
        assert router.standby.state == STANDBY
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# graceful drain + bounded shutdown (satellite 3)
# ---------------------------------------------------------------------------
def test_drain_hands_queued_work_back_as_retryable(config_keys):
    config_keys({"serving.workers": 1})
    router, members, _ = build_fleet(lambda: _slow_ctx(sleep_s=0.1),
                                     replicas=2)
    try:
        outs = []

        def client(i):
            outs.append(router.execute(
                "SELECT SUM(slowid(a)) AS s FROM sleepy", qid=f"dr-{i}"))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.1)
        router.drain("replica-0", wait=False)
        assert members[0].state == DRAINING
        for t in threads:
            t.join(60)
        # every query completed: in-flight finished or was re-dispatched,
        # queued work came back as retryable ShutdownError and re-routed
        assert len(outs) == 3
        assert all(int(o["s"][0]) == 6 for o in outs)
    finally:
        router.shutdown()


def test_shutdown_drain_timeout_fails_stuck_row_udf(config_keys):
    from dask_sql_tpu.serving.runtime import ServingRuntime

    config_keys({"serving.shutdown.drain_timeout_s": 0.3})
    c = _slow_ctx(sleep_s=0.4, rows=6)  # ~2.4s of row-UDF: stuck vs drain
    rt = ServingRuntime.from_config(c.config, metrics=c.metrics)
    c.serving = rt

    def job(ticket):
        return c.sql("SELECT SUM(slowid(a)) AS s FROM sleepy").compute()

    _, fut, ticket = rt.submit(job, qid="stuck-1")
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        with rt._cv:
            if rt._inflight:
                break
        time.sleep(0.01)
    t0 = time.monotonic()
    rt.shutdown(wait=True)
    elapsed = time.monotonic() - t0
    assert elapsed < 2.0, f"drain must be bounded, took {elapsed:.2f}s"
    with pytest.raises(ShutdownError) as ei:
        fut.result(1.0)
    assert ei.value.retryable
    assert "drain timeout" in str(ei.value)


def test_server_drain_endpoint_and_sigterm_protocol():
    import urllib.error
    import urllib.request

    from dask_sql_tpu.server.app import run_server

    srv = run_server(context=_ctx(), host="127.0.0.1", port=0,
                     blocking=False)
    try:
        def health():
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{srv.port}/v1/health") as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        code, body = health()
        assert code == 200 and body["status"] == "ready"
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/drain", data=b"", method="POST")
        with urllib.request.urlopen(req) as r:
            assert json.loads(r.read())["status"] == "draining"
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            code, body = health()
            if code == 503 and body["status"] == "draining":
                break
            time.sleep(0.02)
        assert code == 503 and body["status"] == "draining", body
        # a new statement sheds with a structured 503, not a hang
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/statement",
            data=b"SELECT 1 AS one", method="POST")
        deadline = time.monotonic() + 5.0
        status = None
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(req) as r:
                    status = r.status
            except urllib.error.HTTPError as e:
                status = e.code
                if status == 503:
                    payload = json.loads(e.read())
                    break
            time.sleep(0.02)
        assert status == 503
        assert payload["error"]["errorName"] == "SERVER_SHUTTING_DOWN"
        assert payload["error"]["retryable"] is True
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# flight-recorder dump-path templating (satellite 2)
# ---------------------------------------------------------------------------
def test_expand_dump_path_pid_and_qid():
    p = flight.expand_dump_path("/tmp/flight-{pid}.jsonl")
    assert f"flight-{os.getpid()}.jsonl" in p
    p = flight.expand_dump_path("/tmp/f-{qid}.jsonl", qid="q-1.2")
    assert p.endswith("f-q-1.2.jsonl")
    # hostile qids cannot traverse: separators become underscores
    p = flight.expand_dump_path("/tmp/f-{qid}.jsonl", qid="../../etc/x")
    assert "/etc/" not in p.replace("/tmp/", "")
    assert flight.expand_dump_path("/tmp/f-{qid}.jsonl", qid=None) \
        .endswith("f-unknown.jsonl")


def test_two_writers_get_distinct_dump_files(tmp_path, config_keys):
    # two "replicas" (writers) sharing one dump dir: the {qid} (and {pid})
    # templating gives each failure its own JSONL file — never interleaved
    # appends into one file
    path = str(tmp_path / "flight-{qid}.jsonl")
    config_keys({"observability.flight.dump_path": path})
    assert flight.flush_on_failure("writer-a", "OOM",
                                   config_module.config)
    assert flight.flush_on_failure("writer-b", "TIMEOUT",
                                   config_module.config)
    fa = tmp_path / "flight-writer-a.jsonl"
    fb = tmp_path / "flight-writer-b.jsonl"
    assert fa.exists() and fb.exists()
    for f, qid in ((fa, "writer-a"), (fb, "writer-b")):
        lines = f.read_text().strip().splitlines()
        assert len(lines) == 1
        rec = json.loads(lines[0])  # one intact record per writer
        assert rec["qid"] == qid


# ---------------------------------------------------------------------------
# SHOW REPLICAS
# ---------------------------------------------------------------------------
def test_show_replicas_through_sql():
    router, members, _ = build_fleet(_ctx, replicas=2, standby=True)
    try:
        out = members[0].context.sql("SHOW REPLICAS", return_futures=False)
        names = list(out["Replica"])
        assert names == ["replica-0", "replica-1", "standby"]
        states = dict(zip(out["Replica"], out["State"]))
        assert states["standby"] == "standby"
        liked = members[0].context.sql("SHOW REPLICAS LIKE 'replica-%'",
                                       return_futures=False)
        assert list(liked["Replica"]) == ["replica-0", "replica-1"]
    finally:
        router.shutdown()


def test_show_replicas_empty_without_fleet():
    c = _ctx()
    out = c.sql("SHOW REPLICAS", return_futures=False)
    assert len(out) == 0
    assert list(out.columns) == ["Replica", "State", "Band", "Headroom",
                                 "Routed"]


# ---------------------------------------------------------------------------
# replica kill semantics
# ---------------------------------------------------------------------------
def test_kill_fails_inflight_immediately_with_retryable():
    r = Replica("solo", _slow_ctx(sleep_s=0.2))
    box = {}

    def client():
        try:
            r.run("SELECT SUM(slowid(a)) AS s FROM sleepy", qid="k1")
        except Exception as e:  # noqa: BLE001 — the outcome under test
            box["exc"] = e

    th = threading.Thread(target=client)
    th.start()
    time.sleep(0.25)  # mid-query
    t0 = time.monotonic()
    n = r.kill()
    th.join(30)
    assert n == 1  # the in-flight future was failed by the kill
    assert isinstance(box.get("exc"), ReplicaFailedError)
    assert box["exc"].retryable
    assert time.monotonic() - t0 < 5.0  # kill is immediate, no drain wait
    assert r.state == DEAD
    assert flight.RECORDER.events(name="replica.kill")
