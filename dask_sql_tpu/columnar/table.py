"""Device-resident columnar table: the unit a plan node produces/consumes.

Role parity: one dask DataFrame in the reference (SURVEY.md §1 layer 3).  Here a
table is an ordered mapping of backend column names to `Column`s, all of equal
length, resident in device HBM.  Distribution is handled above this layer
(`dask_sql_tpu.parallel`): a distributed table is this same structure with jax
arrays sharded over a `Mesh` via NamedSharding.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from .column import Column
from .dtypes import SqlType


class Table:
    __slots__ = ("columns", "_num_rows", "row_valid")

    def __init__(self, columns: Dict[str, Column], num_rows: Optional[int] = None,
                 row_valid=None):
        """`row_valid` marks a PADDED table: column buffers are a multiple of
        the shard count (so NamedSharding row specs stay exact end-to-end on
        non-divisible tables), `row_valid` is a same-length device mask of the
        real rows, and `num_rows` stays the logical count.  Padded tables
        exist only at rest (sharded base tables); padding-aware consumers
        (the compiled pipelines) fold `row_valid` into their masks, everyone
        else goes through `depad()`."""
        self.columns: Dict[str, Column] = dict(columns)
        self.row_valid = row_valid
        if num_rows is None:
            num_rows = len(next(iter(self.columns.values()))) if self.columns else 0
        self._num_rows = num_rows
        if row_valid is not None:
            padded = int(row_valid.shape[0])
            assert padded >= num_rows, f"padded {padded} < logical {num_rows}"
            for name, col in self.columns.items():
                assert len(col) == padded, \
                    f"column {name}: {len(col)} != padded {padded}"
        else:
            for name, col in self.columns.items():
                assert len(col) == num_rows, f"column {name}: {len(col)} != {num_rows}"

    @property
    def is_padded(self) -> bool:
        return self.row_valid is not None

    @property
    def padded_rows(self) -> int:
        return int(self.row_valid.shape[0]) if self.row_valid is not None \
            else self._num_rows

    def depad(self) -> "Table":
        """Exact-length view for consumers that index rows positionally.
        The slice keeps a sharded (but no longer block-exact) layout —
        today's pre-padding behavior, paid only on the eager paths."""
        if self.row_valid is None:
            return self
        n = self._num_rows
        return Table({name: c.slice(0, n) for name, c in self.columns.items()}, n)

    # -- construction -------------------------------------------------------
    @staticmethod
    def from_pandas(df, encode=None) -> "Table":
        """``encode``: load-time compressed encodings (columnar/encodings.py)
        — None consults the registration load-scope + config, True forces
        the selection heuristics, False stays dense."""
        cols = {}
        for name in df.columns:
            ser = df[name]
            mask = None
            values = ser.to_numpy()
            if ser.isna().any():
                mask = ~ser.isna().to_numpy()
                if values.dtype.kind in ("i", "u", "b"):
                    pass  # no NaN possible; mask already captured
            if str(ser.dtype) in ("string", "str") or ser.dtype == object:
                values = ser.astype(object).to_numpy()
            elif values.dtype.kind not in ("O", "U", "S", "M", "m", "f", "i", "u", "b"):
                values = ser.astype(object).to_numpy()
            cols[str(name)] = Column.from_numpy(values, mask, encode=encode)
        return Table(cols, len(df))

    @staticmethod
    def from_arrow(arrow_table) -> "Table":
        from . import interop

        return interop.arrow_to_table(arrow_table)

    # -- basic properties ---------------------------------------------------
    @property
    def num_rows(self) -> int:
        return self._num_rows

    @property
    def column_names(self) -> List[str]:
        return list(self.columns.keys())

    def __len__(self) -> int:
        return self._num_rows

    def __contains__(self, name: str) -> bool:
        return name in self.columns

    def __getitem__(self, name: str) -> Column:
        return self.columns[name]

    # -- transformations (all return new Tables; columns are immutable) -----
    def select(self, names: Sequence[str]) -> "Table":
        return Table({n: self.columns[n] for n in names}, self._num_rows,
                     self.row_valid)

    def assign(self, **new_cols: Column) -> "Table":
        cols = dict(self.columns)
        cols.update(new_cols)
        return Table(cols, self._num_rows, self.row_valid)

    def rename(self, mapping: Dict[str, str]) -> "Table":
        return Table({mapping.get(n, n): c for n, c in self.columns.items()},
                     self._num_rows, self.row_valid)

    def decode(self) -> "Table":
        """Materialize every encoded column as PLAIN (eager-operator view).
        Identity when nothing is encoded — the common case stays free."""
        from .encodings import Encoding

        if all(c.encoding is Encoding.PLAIN for c in self.columns.values()):
            return self
        return Table({n: c.decode() for n, c in self.columns.items()},
                     self._num_rows, self.row_valid)

    def has_encoded_columns(self) -> bool:
        from .encodings import Encoding

        return any(c.encoding is not Encoding.PLAIN
                   for c in self.columns.values())

    def filter(self, mask) -> "Table":
        # one nonzero for the whole table, then integer gathers per column —
        # per-column boolean indexing pays the bool->index expansion N times
        mask = jnp.asarray(mask)
        if self.row_valid is not None and \
                int(mask.shape[0]) == self.padded_rows:
            # padded-frame mask: pad rows must never pass, and the gather
            # frame must match the mask frame
            indices = jnp.nonzero(mask & self.row_valid)[0]
            return Table({n: c.take(indices) for n, c in self.columns.items()},
                         int(indices.shape[0]))
        src = self.depad()
        indices = jnp.nonzero(mask)[0]
        return Table({n: c.take(indices) for n, c in src.columns.items()},
                     int(indices.shape[0]))

    def take(self, indices) -> "Table":
        # indices are LOGICAL row positions (< num_rows); a padded table
        # gathers from its exact-length view
        src = self.depad()
        indices = jnp.asarray(indices)
        return Table({n: c.take(indices) for n, c in src.columns.items()},
                     int(indices.shape[0]))

    def slice(self, start: int, stop: int) -> "Table":
        src = self.depad()
        stop = min(stop, self._num_rows)
        start = min(start, stop)
        return Table({n: c.slice(start, stop) for n, c in src.columns.items()}, stop - start)

    def head(self, n: int) -> "Table":
        return self.slice(0, n)

    @staticmethod
    def concat(tables: Sequence["Table"]) -> "Table":
        """Vertical concatenation (UNION ALL primitive)."""
        from .concat import concat_tables

        return concat_tables(tables)

    # -- host materialization ----------------------------------------------
    def to_pandas(self):
        import pandas as pd

        data = self._host_columns()
        if not data:
            return pd.DataFrame(index=range(self._num_rows))
        return pd.DataFrame(data)

    def _host_columns(self):
        """{name: numpy} with NULL decoding.

        On accelerator backends every device buffer rides ONE packed
        transfer (per-column pulls each cost a dispatch round trip, which
        dominates on a tunneled chip); host-resident columns and the CPU
        backend use the plain per-column path."""
        if self.row_valid is not None:
            return self.depad()._host_columns()
        import os

        import jax

        cols = self.columns
        force = os.environ.get("DSQL_PACK_TO_PANDAS") == "1"  # for tests
        if not cols or self._num_rows == 0 or (
                jax.default_backend() == "cpu" and not force):
            return {n: c.to_numpy() for n, c in cols.items()}
        from .pack import packed_host_arrays

        bufs = []
        for c in cols.values():
            bufs.append(c.data)
            if c.validity is not None:
                bufs.append(c.validity)
        from ..resilience.errors import QueryError

        try:
            host = packed_host_arrays(bufs)
        except QueryError:
            # taxonomy failures (a dropped tunneled transfer — fault site
            # ``d2h``) must keep their retry semantics: the serving
            # worker's backoff absorbs them; a silent per-column fallback
            # would hide the drop AND re-pay the transfer N times
            raise
        except Exception:  # dsql: allow-broad-except — backend pack quirk -> per-column
            host = None
        if host is None:
            return {n: c.to_numpy() for n, c in cols.items()}
        # decode errors propagate: a silent fallback here would double-pay
        # the transfer on every call while hiding the defect
        out = {}
        i = 0
        for n, c in cols.items():
            data = host[i]
            i += 1
            mask = None
            if c.validity is not None:
                mask = ~host[i]
                i += 1
            out[n] = c.decode_host(data, mask)
        return out

    def to_arrow(self):
        from . import interop

        return interop.table_to_arrow(self.depad())

    def __repr__(self) -> str:
        cols = ", ".join(f"{n}:{c.sql_type.value}" for n, c in self.columns.items())
        return f"Table[{self._num_rows} rows]({cols})"
