"""Config system tests (parity: reference tests/unit/test_config.py)."""
import pytest


def test_defaults_present():
    from dask_sql_tpu import config

    assert config.get("sql.identifier.case_sensitive") is True
    assert config.get("sql.optimize") is True
    assert config.get("sql.sort.topk-nelem-limit") == 1000000
    assert config.get("sql.predicate_pushdown") is True
    assert config.get("sql.dynamic_partition_pruning") is True
    assert config.get("sql.optimizer.fact_dimension_ratio") == 0.7


def test_set_context_manager():
    from dask_sql_tpu import config

    assert config.get("sql.optimize") is True
    with config.set({"sql.optimize": False}):
        assert config.get("sql.optimize") is False
        with config.set({"sql.optimize": True}):
            assert config.get("sql.optimize") is True
        assert config.get("sql.optimize") is False
    assert config.get("sql.optimize") is True


def test_unknown_key_default():
    from dask_sql_tpu import config

    assert config.get("sql.not-a-key", 42) == 42


def test_per_query_config_options():
    import pandas as pd

    from dask_sql_tpu import Context

    c = Context()
    c.create_table("t", pd.DataFrame({"a": [1, 2, 3]}))
    result = c.sql("SELECT SUM(a) AS s FROM t",
                   config_options={"sql.optimize": False}, return_futures=False)
    assert result["s"][0] == 6


def test_documented_keys_registry_covers_defaults():
    from dask_sql_tpu.config import (DEFAULTS, DOCUMENTED_KEYS, KeySpec,
                                     is_documented_key)

    assert set(DOCUMENTED_KEYS) == set(DEFAULTS)
    spec = DOCUMENTED_KEYS["sql.optimize"]
    assert isinstance(spec, KeySpec)
    assert spec.default is True and bool in spec.types
    # None-default keys still declare the type a non-None value takes
    assert int in DOCUMENTED_KEYS["serving.deadline_s"].types \
        or float in DOCUMENTED_KEYS["serving.deadline_s"].types
    assert is_documented_key("sql.optimize")
    assert not is_documented_key("sql.not-a-key")


def test_strict_config_warns_once_per_unregistered_key(caplog):
    import logging

    from dask_sql_tpu import config

    # off (the default): silent
    with caplog.at_level(logging.WARNING, logger="dask_sql_tpu.config"):
        assert config.get("strictcfg.test.off", 7) == 7
    assert not caplog.records

    with config.set({"analysis.strict_config": True}):
        with caplog.at_level(logging.WARNING, logger="dask_sql_tpu.config"):
            assert config.get("strictcfg.test.on", 7) == 7
            assert config.get("strictcfg.test.on", 7) == 7  # second: silent
    warned = [r for r in caplog.records
              if "strictcfg.test.on" in r.getMessage()]
    assert len(warned) == 1
    assert "DOCUMENTED_KEYS" in warned[0].getMessage()
