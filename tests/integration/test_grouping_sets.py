"""GROUPING SETS / ROLLUP / CUBE tests (parity: aggregate.rs getGroupSets)."""
import numpy as np
import pandas as pd
import pytest


@pytest.fixture
def gdf(c):
    df = pd.DataFrame({
        "g1": ["a", "a", "b", "b"],
        "g2": ["x", "y", "x", "y"],
        "v": [1, 2, 3, 4],
    })
    c.create_table("gs", df)
    return df


def test_rollup(c, gdf):
    result = c.sql(
        "SELECT g1, g2, SUM(v) AS s FROM gs GROUP BY ROLLUP (g1, g2)"
    ).compute()
    # (g1,g2): 4 rows, (g1): 2 rows, (): 1 row
    assert len(result) == 7
    total = result[pd.isna(result.g1) & pd.isna(result.g2)]
    assert total["s"].iloc[0] == 10
    g1_only = result[~pd.isna(result.g1) & pd.isna(result.g2)].sort_values("g1")
    assert list(g1_only["s"]) == [3, 7]


def test_cube(c, gdf):
    result = c.sql(
        "SELECT g1, g2, SUM(v) AS s FROM gs GROUP BY CUBE (g1, g2)"
    ).compute()
    # 4 + 2 + 2 + 1
    assert len(result) == 9
    g2_only = result[pd.isna(result.g1) & ~pd.isna(result.g2)].sort_values("g2")
    assert list(g2_only["s"]) == [4, 6]


def test_grouping_sets(c, gdf):
    result = c.sql(
        "SELECT g1, g2, SUM(v) AS s FROM gs GROUP BY GROUPING SETS ((g1), (g2), ())"
    ).compute()
    assert len(result) == 2 + 2 + 1
    assert result["s"].sum() == 10 * 3  # each set sums to 10


def test_rollup_with_order(c, gdf):
    result = c.sql(
        "SELECT g1, SUM(v) AS s FROM gs GROUP BY ROLLUP (g1) ORDER BY s DESC"
    ).compute()
    assert list(result["s"]) == [10, 7, 3]


def test_grouping_function_rollup(c):
    """GROUPING() bitmask per grouping set (leftmost arg = MSB).
    Parity: reference surfaces DataFusion grouping-id via aggregate.rs
    getGroupSets; lowered here during binder expansion."""
    import pandas as pd

    df = pd.DataFrame({"a": ["x", "x", "y"], "b": ["p", "q", "p"],
                       "v": [1.0, 2.0, 3.0]})
    c.create_table("gfr", df)
    r = c.sql(
        "SELECT a, b, GROUPING(a) AS ga, GROUPING(b) AS gb, "
        "GROUPING(a, b) AS gab, SUM(v) AS s "
        "FROM gfr GROUP BY ROLLUP(a, b) ORDER BY a, b"
    ).compute()
    # detail rows: 0/0/0 ; per-a subtotals: 0/1/1 ; grand total: 1/1/3
    import numpy as np

    assert list(r["gab"]) == [0, 0, 1, 0, 1, 3]
    assert list(r["ga"]) == [0, 0, 0, 0, 0, 1]
    assert list(r["gb"]) == [0, 0, 1, 0, 1, 1]
    total = r[r["gab"] == 3]["s"].iloc[0]
    np.testing.assert_allclose(total, 6.0)


def test_grouping_function_plain_group_by(c):
    import pandas as pd

    df = pd.DataFrame({"a": ["x", "y"], "v": [1.0, 2.0]})
    c.create_table("gfp", df)
    r = c.sql("SELECT a, GROUPING(a) AS g FROM gfp GROUP BY a").compute()
    assert list(r["g"]) == [0, 0]


def test_having_references_select_alias(c):
    """HAVING may reference a select alias of an aggregate (TPC-DS q33/q56/
    q60/q71 shape; the reference resolves via DataFusion SqlToRel)."""
    import pandas as pd

    df = pd.DataFrame({"g": ["a", "a", "b", "c"], "v": [1.0, 2.0, 7.0, 10.0]})
    c.create_table("hav", df)
    r = c.sql("SELECT g, SUM(v) AS total FROM hav GROUP BY g "
              "HAVING total > 4 ORDER BY total DESC").compute()
    assert list(r["g"]) == ["c", "b"]
    # a real column named like the alias wins over the alias
    df2 = pd.DataFrame({"g": ["a", "b"], "total": [1.0, 100.0]})
    c.create_table("hav2", df2)
    r2 = c.sql("SELECT g, SUM(total) AS total FROM hav2 "
               "GROUP BY g, total HAVING total > 50").compute()
    assert list(r2["g"]) == ["b"]
