"""The fleet router: health-gated cost-aware routing with failover.

The router fronts N `Replica`s and makes replica death and saturation
both non-events:

- **Routing** (`execute`): every dispatch picks the replica whose packing
  budget fits the query's cost hint — candidates are the health-gated
  routable replicas ordered by (fits-the-hint, ledger headroom,
  predicted drain); a replica whose admission queue is at bound makes
  the router SPILL to the next peer instead of surfacing the 429 (the
  queue-full error only reaches the client when every live replica is
  saturated, with the largest Retry-After of the set).
- **Failover**: every routed query carries an idempotency key — the
  client qid plus the engine's own result-cache key ingredients (family
  fingerprint + parameter values + table epochs) on the replica side —
  so when a replica dies or times out mid-query the router re-dispatches
  to a survivor with bounded retry/backoff
  (``fleet.failover.max_attempts`` / ``fleet.failover.base_s``) and the
  survivor's result cache dedupes re-execution of anything it already
  answered.  Only retryable taxonomy codes re-dispatch; user errors and
  non-retryable failures propagate on first throw.
- **Warm-standby promotion**: on replica death the router promotes the
  standby (fleet/replication.py keeps it ingesting snapshots + the
  persistent compile cache + profiles), replaying any writes the standby
  missed — epoch-fenced, so a replay can never double-apply.
- **Write fan-out** (INSERT INTO): writes apply on EVERY live replica,
  each stamped with the router's per-table write sequence as the
  expected delta epoch (`Replica.apply_write`): exactly-once no matter
  how many times failover retries the statement.  Statement
  classification is PARSER-backed (never a regex decision): a single
  ``InsertInto`` fans out, any other mutating statement is rejected with
  a structured `UnroutableStatementError` instead of silently executing
  on one replica and diverging the fleet.  Writes are bound on a live
  replica BEFORE they are sequenced, and an entry whose apply fails
  non-retryably is tombstoned — a bad statement can never wedge the
  per-table write log for every later write.
"""
from __future__ import annotations

import logging
import re
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..runtime import locks
from ..resilience.errors import (
    ReplicaFailedError,
    ShutdownError,
    UnroutableStatementError,
    classify,
)
from ..serving.admission import QueueFullError
from .replica import DEAD, READY, Replica

logger = logging.getLogger(__name__)

#: cheap trigger deciding which texts pay the router-side parse: only a
#: statement that MIGHT mutate is parsed for classification.  The regex is
#: never the decider — quoted names, leading comments etc. all reach the
#: parser, whose AST says what the statement actually is.
_MUTATION_TRIGGER_RE = re.compile(r"\b(insert|create|drop|alter|use)\b",
                                  re.IGNORECASE)


@dataclass
class _WriteEntry:
    """One sequenced slot in a table's write log.  ``tombstone`` marks an
    entry whose apply failed non-retryably (user error that slipped past
    pre-validation): catch-up replays advance the epoch past the slot
    without re-executing — the poison-pill guard."""

    sql: str
    qid: str
    tombstone: bool = False
    error: Optional[str] = None


class Router:
    """Routes per-tenant traffic across a replica fleet."""

    def __init__(self, replicas: List[Replica],
                 standby: Optional[Replica] = None,
                 metrics=None, config=None):
        from ..serving.metrics import MetricsRegistry

        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.replicas: List[Replica] = list(replicas)
        self.standby = standby
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        from .. import config as config_module

        self.config = config if config is not None else config_module.config
        # rank 20: membership/epoch state — taken from under _apply_lock
        # (rank 10) during fan-out and promotion, never the reverse
        self._lock = locks.named_lock("fleet.router.state")
        #: serializes write APPLICATION (fan-out and promotion replay):
        #: sequencing happens under `_lock`, but applies must land in
        #: sequence order or concurrent writers would trip each other's
        #: epoch fences ("behind, replay required") on every replica.
        #: rank 10: the fleet's outermost lock — held across replica
        #: apply/replay/promote, which takes replica + context locks
        self._apply_lock = locks.named_lock("fleet.router.apply")
        #: global per-table write sequence: the fence every fanned-out
        #: write carries, and the replay source for promoted standbys
        self._write_log: Dict[Tuple[str, str], List[_WriteEntry]] = {}
        #: write idempotency index: (table_key, client qid) -> sequence
        #: slot.  Keyed on the QID, never the SQL text — two intentional
        #: executions of an identical INSERT under distinct qids are two
        #: writes; a retry under the original qid dedupes to its slot
        self._seq_by_qid: Dict[Tuple[Tuple[str, str], str], int] = {}
        #: per-replica suspect deadlines (monotonic): a replica that just
        #: failed a dispatch sorts LAST in candidate order until the
        #: cooldown expires, so failover lands on a different member
        #: instead of burning every attempt on one wedged replica
        self._suspect: Dict[str, float] = {}
        #: the table's delta epoch when the router first saw it — fences
        #: are base + position in the log, so a fleet built over tables
        #: with prior epochs keeps counting from where they were
        self._epoch_base: Dict[Tuple[str, str], int] = {}
        #: per-replica routed-query tally (SHOW REPLICAS)
        self._routed: Dict[str, int] = {}
        for r in self.replicas + ([standby] if standby is not None else []):
            r.context.fleet_router = self
        self.metrics.gauge("fleet.replicas", len(self._live()))

    # -------------------------------------------------------------- picking
    def _live(self) -> List[Replica]:
        return [r for r in self.replicas if r.state == READY]

    def _candidates(self, cost_bytes: int,
                    avoid: Tuple[str, ...] = ()) -> List[Replica]:
        """Routable replicas, best first: replicas whose headroom fits the
        query's provable cost hint before ones that would overcommit, then
        by descending headroom, then by the scheduler's predicted drain
        (spill lands on the replica that frees up soonest).  Members in
        ``avoid`` (already failed THIS query) or inside their suspect
        cooldown sort last — still eligible when nothing else is live, but
        never re-picked first over an untried peer."""
        cands = [r for r in self.replicas if r.routable]
        now = time.monotonic()
        with self._lock:
            suspects = {n for n, until in self._suspect.items()
                        if until > now}

        def key(r: Replica):
            headroom = r.headroom_bytes()
            fits = headroom is None or headroom >= cost_bytes
            drain = r.predicted_drain_s()
            return (r.name in avoid or r.name in suspects,
                    not fits,
                    -(headroom if headroom is not None else float("inf")),
                    drain if drain is not None else 0.0)

        return sorted(cands, key=key)

    def _cost_hint(self, sql: str, config_options):
        for r in self._live():
            try:
                return r.context.cost_hint(sql, config_options)
            except Exception:  # dsql: allow-broad-except — advisory hint
                continue
        return None

    # ------------------------------------------------------------- failures
    def _note_failure(self, replica: Replica) -> None:
        """A dispatch to ``replica`` failed with a replica-level error:
        mark it suspect (a timed-out replica stays READY but must not be
        the next failover's first pick), refresh the live gauge and
        promote the standby if the replica is actually dead (vs merely
        draining/slow)."""
        cooldown = float(self.config.get(
            "fleet.failover.suspect_cooldown_s", 5.0) or 0.0)
        if cooldown > 0:
            with self._lock:
                self._suspect[replica.name] = time.monotonic() + cooldown
        self.metrics.gauge("fleet.replicas", len(self._live()))
        if replica.state == DEAD:
            self.maybe_promote()

    def maybe_promote(self) -> Optional[Replica]:
        """Promote a ready warm standby into the serving set (idempotent;
        no-op when there is no standby, it is not warm yet, or
        ``fleet.standby.auto_promote`` is off).  Missed writes replay
        BEFORE the standby takes traffic — epoch-fenced, exactly-once."""
        from ..observability import flight

        with self._lock:
            standby = self.standby
            if standby is None or not bool(self.config.get(
                    "fleet.standby.auto_promote", True)):
                return None
            warm = getattr(standby.context, "warmup", None)
            if warm is not None and not warm.ready:
                return None
            self.standby = None
        # replay + promote + join all under the APPLY lock: no write can
        # be sequenced-and-applied between the replay and the append, so
        # a freshly promoted member can never have missed a write and
        # serve stale reads until the next catch-up
        with self._apply_lock:
            try:
                self._replay_writes(standby)
            except ReplicaFailedError:
                logger.warning("standby %s failed during promotion replay;"
                               " dropping it from the fleet", standby.name,
                               exc_info=True)
                return None
            standby.promote()
            with self._lock:
                self.replicas.append(standby)
        flight.record("fleet.promote", replica=standby.name)
        self.metrics.inc("fleet.promote")
        self.metrics.gauge("fleet.replicas", len(self._live()))
        logger.info("promoted standby replica %s into the serving set",
                    standby.name)
        return standby

    def _replay_writes(self, replica: Replica) -> None:
        """Replay the write-log tail ``replica`` missed.  Caller holds
        ``_apply_lock`` (lock order: _apply_lock, then _lock)."""
        with self._lock:
            log_snapshot = {k: list(v) for k, v in self._write_log.items()}
            bases = dict(self._epoch_base)
        for table_key, log in log_snapshot.items():
            base = bases.get(table_key, 0)
            # the snapshot a standby restored from carries the table
            # epochs it captured (checkpoint.py), so `have` is exactly
            # how many sequenced writes it has seen — replay the tail
            have = replica.context.table_epoch(*table_key) - base
            for i in range(max(0, have), len(log)):
                self._apply_entry(replica, table_key, base, i, log[i])
                self.metrics.inc("fleet.write.replayed")

    # ------------------------------------------------------- classification
    def _classify(self, sql: str):
        """Parser-backed statement classification (never a regex decision):
        returns ``("write", InsertInto)`` for a single-statement INSERT
        INTO, ``("mutation", Statement)`` for any other mutating statement
        (or a multi-statement script containing one) — the router rejects
        those — and ``("read", None)`` otherwise.  A text that fails to
        parse routes as a read: the replica surfaces the real parse error
        to the client, and an unparseable text cannot be a mutation."""
        if not _MUTATION_TRIGGER_RE.search(sql):
            return ("read", None)
        from ..planner import sqlast as a
        from ..planner.parser import parse_sql

        try:
            stmts = parse_sql(sql)
        except Exception:  # dsql: allow-broad-except — replica reports it
            return ("read", None)
        mutation_types = (
            a.InsertInto, a.CreateTableWith, a.CreateTableAs, a.DropTable,
            a.CreateSchema, a.DropSchema, a.AlterSchema, a.AlterTable,
            a.UseSchema, a.CreateModel, a.DropModel, a.CreateExperiment)
        if len(stmts) == 1 and isinstance(stmts[0], a.InsertInto):
            return ("write", stmts[0])
        for stmt in stmts:
            if isinstance(stmt, mutation_types):
                return ("mutation", stmt)
        return ("read", None)

    # ------------------------------------------------------------ execution
    def execute(self, sql: str, qid: Optional[str] = None,
                priority_class: str = "interactive",
                config_options: Optional[Dict[str, Any]] = None,
                tenant: Optional[str] = None):
        """Route one statement; blocks for the result.  Reads re-dispatch
        across replicas on retryable replica failures; single-statement
        INSERT INTO fans out to every live replica with epoch fencing;
        any other mutation is rejected with a structured user error
        rather than silently diverging the fleet."""
        qid = qid or str(uuid.uuid4())
        kind, stmt = self._classify(sql)
        if kind == "write":
            return self._write(sql, stmt, qid)
        if kind == "mutation":
            self.metrics.inc("fleet.write.unroutable")
            raise UnroutableStatementError(
                f"fleet router cannot fan out {type(stmt).__name__}: only "
                f"single-statement INSERT INTO mutates through the router;"
                f" apply DDL to every replica at fleet build time",
                query_id=qid)
        return self._read(sql, qid, priority_class, config_options, tenant)

    def _read(self, sql: str, qid: str, priority_class: str,
              config_options, tenant):
        from ..observability import flight

        cost = self._cost_hint(sql, config_options)
        cost_bytes = int(getattr(cost, "bytes_lo", 0) or 0)
        if cost is not None and tenant:
            cost.tenant = tenant
        attempts = max(1, int(self.config.get(
            "fleet.failover.max_attempts", 3) or 1))
        base_s = float(self.config.get("fleet.failover.base_s", 0.02) or 0.0)
        last_exc: Optional[BaseException] = None
        avoid: set = set()  # members that already failed THIS query
        for attempt in range(attempts):
            order = self._candidates(cost_bytes, avoid=tuple(avoid))
            if not order:
                # nothing routable: a promotion may mint a candidate
                promoted = self.maybe_promote()
                if promoted is not None:
                    order = self._candidates(cost_bytes, avoid=tuple(avoid))
            if not order:
                raise last_exc if last_exc is not None else \
                    ReplicaFailedError("no routable replica in the fleet",
                                       query_id=qid)
            queue_full: List[QueueFullError] = []
            failed_over = False
            for replica in order:
                flight.record("fleet.route", qid=qid, replica=replica.name,
                              attempt=attempt)
                self.metrics.inc("fleet.route")
                self.metrics.inc(f"fleet.routed.{replica.name}")
                with self._lock:
                    self._routed[replica.name] = \
                        self._routed.get(replica.name, 0) + 1
                try:
                    out = replica.run(sql, qid=qid,
                                      priority_class=priority_class,
                                      config_options=config_options,
                                      cost=cost)
                    with self._lock:
                        self._suspect.pop(replica.name, None)
                    return out
                except QueueFullError as e:
                    # saturation is a ROUTING event, not a client error:
                    # spill to the next peer (never a failover attempt)
                    self.metrics.inc("fleet.route.spill")
                    queue_full.append(e)
                    continue
                except (ReplicaFailedError, ShutdownError) as e:
                    # replica died / drained / timed out mid-query:
                    # bounded failover to a survivor; the survivor's
                    # result cache dedupes re-execution
                    last_exc = e
                    failed_over = True
                    avoid.add(replica.name)
                    flight.record("fleet.failover", qid=qid,
                                  replica=replica.name,
                                  code=getattr(e, "code", None))
                    self.metrics.inc("fleet.failover")
                    self._note_failure(replica)
                    break
            else:
                if queue_full:
                    # EVERY live replica is saturated: now — and only
                    # now — the shed surfaces, with the most pessimistic
                    # Retry-After of the fleet
                    worst = max(queue_full, key=lambda e: e.retry_after_s)
                    raise worst
            if failed_over and attempt + 1 < attempts and base_s > 0:
                time.sleep(base_s * (2 ** attempt))
        assert last_exc is not None
        raise last_exc

    # --------------------------------------------------------------- writes
    def _table_key(self, name_parts: List[str]) -> Tuple[str, str]:
        parts = [p for p in (name_parts or []) if p]
        if len(parts) >= 2:
            return (parts[-2], parts[-1])
        table = parts[0] if parts else ""
        schema = self._live()[0].context.schema_name if self._live() \
            else "root"
        return (schema, table)

    def _apply_entry(self, replica: Replica, table_key: Tuple[str, str],
                     base: int, i: int, entry: _WriteEntry):
        """Apply write-log slot ``i`` on one replica (caller holds
        ``_apply_lock``).  Returns ``(result, poison_error)``.  A
        tombstoned entry advances the replica's epoch past the slot
        without executing.  An apply that fails NON-retryably (a user
        error that slipped past pre-validation, e.g. an incompatible
        column set) POISONS the slot: the entry becomes a tombstone so
        every later catch-up replay skips it instead of re-raising the
        same error forever, and the structured error comes back for the
        sequencing client.  Retryable failures (replica died, transient
        resource exhaustion) re-raise as `ReplicaFailedError` and leave
        the entry live — this replica catches up on the next write."""
        if entry.tombstone:
            replica.apply_noop(table_key, base + i, qid=entry.qid)
            return None, None
        try:
            return replica.apply_write(entry.sql, table_key, base + i,
                                       qid=entry.qid), None
        except ReplicaFailedError:
            raise
        except Exception as exc:  # dsql: allow-broad-except — split below
            err = classify(exc, query_id=entry.qid)
            if err.retryable:
                raise ReplicaFailedError(
                    f"replica {replica.name} failed write {entry.qid} "
                    f"({err.code}); will catch up",
                    query_id=entry.qid) from exc
            entry.tombstone = True
            entry.error = f"{err.code}: {exc}"
            self.metrics.inc("fleet.write.poisoned")
            logger.warning(
                "write %s poisoned the %s.%s log at slot %d (%s); "
                "tombstoned so later writes are not wedged",
                entry.qid, table_key[0], table_key[1], i, err.code)
            replica.apply_noop(table_key, base + i, qid=entry.qid)
            return None, err

    def _write(self, sql: str, stmt, qid: str):
        """Fan a write out to every live replica under one epoch fence.
        The statement lands exactly once per replica no matter how many
        times a client or the failover loop retries it under the same
        qid: the fence is the router's global per-table write sequence,
        and `apply_write` no-ops when a replica's epoch already advanced
        past it.  An identical statement under a DISTINCT qid is a new
        write with its own sequence slot."""
        table_key = self._table_key(stmt.table)
        with self._lock:
            sequenced = (table_key, qid) in self._seq_by_qid
        if not sequenced:
            # bind on a live member BEFORE sequencing: a statement that
            # cannot bind (unknown table/column, type error) must never
            # occupy a fence slot — the poison-pill guard's front door
            live = self._live()
            if live:
                live[0].validate_write(sql, stmt, table_key, qid=qid)
        with self._lock:
            log = self._write_log.setdefault(table_key, [])
            if table_key not in self._epoch_base:
                live = self._live()
                self._epoch_base[table_key] = \
                    live[0].context.table_epoch(*table_key) if live else 0
            base = self._epoch_base[table_key]
            idx = self._seq_by_qid.get((table_key, qid))
            if idx is None:
                idx = len(log)
                log.append(_WriteEntry(sql=sql, qid=qid))
                self._seq_by_qid[(table_key, qid)] = idx
        result = None
        applied = 0
        poison = None
        failed: List[Replica] = []
        with self._apply_lock:
            with self._lock:
                pending = list(self._write_log[table_key])
            for replica in list(self.replicas):
                if replica.state != READY:
                    continue
                try:
                    # bring this replica fully up to date in sequence
                    # order: a concurrent writer may have sequenced ahead
                    # of us, and its statements must land first or the
                    # epoch fence would (correctly) reject ours as early
                    have = replica.context.table_epoch(*table_key) - base
                    for i in range(max(0, have), len(pending)):
                        out, err = self._apply_entry(replica, table_key,
                                                     base, i, pending[i])
                        if err is not None and i == idx:
                            poison = err
                        if i == idx and out is not None and result is None:
                            result = out
                    applied += 1
                except ReplicaFailedError:
                    failed.append(replica)
                    continue
        for replica in failed:
            # outside the apply lock: a promotion triggered here replays
            # the write log, which re-takes it
            self._note_failure(replica)
        if poison is not None:
            # OUR statement was the poison: the structured user error
            # reaches this client; the log stays healthy for later writes
            raise poison
        if applied == 0:
            raise ReplicaFailedError(
                f"write {qid} applied on no replica", query_id=qid)
        return result

    # -------------------------------------------------------------- control
    def find(self, name: str) -> Optional[Replica]:
        for r in self.replicas:
            if r.name == name:
                return r
        if self.standby is not None and self.standby.name == name:
            return self.standby
        return None

    def drain(self, name: str, wait: bool = True) -> bool:
        """Gracefully drain one replica out of the serving set."""
        replica = self.find(name)
        if replica is None:
            return False
        replica.drain(wait=wait)
        self.metrics.gauge("fleet.replicas", len(self._live()))
        self.maybe_promote()
        return True

    def kill(self, name: str) -> bool:
        """Chaos entry point: kill -9 one replica."""
        replica = self.find(name)
        if replica is None:
            return False
        replica.kill()
        self._note_failure(replica)
        return True

    def shutdown(self) -> None:
        """Drain every member (tests/chaos teardown)."""
        for r in list(self.replicas) + \
                ([self.standby] if self.standby is not None else []):
            r.shutdown()

    # ------------------------------------------------------------- readouts
    def rows(self) -> List[Tuple[str, str, str, str, str]]:
        """(Replica, State, Band, Headroom, Routed) rows — SHOW REPLICAS."""
        out = []
        members = list(self.replicas)
        if self.standby is not None:
            members.append(self.standby)
        with self._lock:
            routed = dict(self._routed)
        for r in members:
            health = r.health()
            headroom = health.get("headroomBytes")
            out.append((r.name, health.get("status", r.state),
                        str(health.get("band", "-")),
                        "-" if headroom is None else str(int(headroom)),
                        str(routed.get(r.name, 0))))
        return out

    def snapshot(self) -> Dict[str, Any]:
        return {
            "replicas": [
                {"name": r.name, "state": r.state, "health": r.health()}
                for r in self.replicas],
            "standby": None if self.standby is None else {
                "name": self.standby.name,
                "state": self.standby.state,
                "health": self.standby.health()},
            "writeLog": {f"{s}.{t}": len(log) for (s, t), log
                         in self._write_log.items()},
        }
