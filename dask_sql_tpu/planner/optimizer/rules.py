"""Optimizer rules.

Role parity: the reference's DataFusion rule pipeline (optimizer.rs:53-98):
SimplifyExpressions, DecorrelateWhereExists/In (decorrelate_where_*.rs),
EliminateCrossJoin, EliminateLimit, FilterNullJoinKeys, PushDownLimit,
PushDownFilter, PushDownProjection/EliminateProjection.  Implemented over our
plan IR; each rule returns a (possibly) new plan.
"""
from __future__ import annotations

from dataclasses import replace
from typing import Dict, List, Optional, Set, Tuple

from ...columnar.dtypes import SqlType
from .. import plan as p
from ..binder import _OuterRef, split_join_condition
from ..expressions import (
    AggExpr,
    CaseExpr,
    Cast,
    ColumnRef,
    ExistsExpr,
    Expr,
    Field,
    InListExpr,
    InSubqueryExpr,
    Literal,
    ScalarFunc,
    ScalarSubqueryExpr,
    SortKey,
    WindowExpr,
    referenced_columns,
    remap_columns,
    shift_columns,
    transform,
    walk,
)


class Rule:
    def apply(self, plan, config, catalog):
        return self.rewrite(plan, config, catalog)

    def rewrite(self, plan, config, catalog):
        return None


def _rewrite_children(plan, fn):
    kids = plan.inputs()
    if not kids:
        return plan
    new_kids = [fn(k) for k in kids]
    if all(a is b for a, b in zip(kids, new_kids)):
        return plan
    return plan.with_inputs(new_kids)


# ---------------------------------------------------------------------------
# SimplifyExpressions: constant folding + boolean simplification
# ---------------------------------------------------------------------------
_FOLDABLE = {
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
    "lt": lambda a, b: a < b,
    "le": lambda a, b: a <= b,
    "gt": lambda a, b: a > b,
    "ge": lambda a, b: a >= b,
}


def simplify_expr(e: Expr) -> Expr:
    def fn(x: Expr) -> Expr:
        if isinstance(x, ScalarFunc):
            args = x.args
            if x.op in ("and", "or") and len(args) == 2:
                a, b = args
                if isinstance(a, Literal) and isinstance(a.value, bool):
                    if x.op == "and":
                        return b if a.value else Literal(False, SqlType.BOOLEAN)
                    return Literal(True, SqlType.BOOLEAN) if a.value else b
                if isinstance(b, Literal) and isinstance(b.value, bool):
                    if x.op == "and":
                        return a if b.value else Literal(False, SqlType.BOOLEAN)
                    return Literal(True, SqlType.BOOLEAN) if b.value else a
            if x.op == "not" and isinstance(args[0], Literal) and isinstance(args[0].value, bool):
                return Literal(not args[0].value, SqlType.BOOLEAN)
            if x.op == "not" and isinstance(args[0], ScalarFunc) and args[0].op == "not":
                return args[0].args[0]
            if (x.op in _FOLDABLE and len(args) == 2
                    and all(isinstance(a, Literal) and a.value is not None
                            and not isinstance(a.value, str) for a in args)):
                try:
                    val = _FOLDABLE[x.op](args[0].value, args[1].value)
                    return Literal(val, x.sql_type)
                except (ArithmeticError, ValueError, TypeError):
                    return x  # unfoldable literal pair: leave for runtime
        if isinstance(x, Cast) and isinstance(x.arg, Literal):
            from ..binder import _cast_literal

            try:
                if x.arg.value is None:
                    return Literal(None, x.sql_type)
                lit = _cast_literal(Literal(x.arg.value, x.arg.sql_type), x.sql_type)
                return Literal(lit.value, x.sql_type)
            except (ArithmeticError, ValueError, TypeError, KeyError):
                return x  # uncastable literal: leave the CAST for runtime
        if isinstance(x, Cast) and x.arg.sql_type == x.sql_type:
            return x.arg
        return x

    return transform(e, fn)


def _map_node_exprs(plan, fn):
    """Apply fn to every expression held by this node (not recursive)."""
    if isinstance(plan, p.Projection):
        return p.Projection(plan.input, [fn(e) for e in plan.exprs], plan.schema)
    if isinstance(plan, p.Filter):
        return p.Filter(plan.input, fn(plan.predicate), plan.schema)
    if isinstance(plan, p.Join):
        on = [(fn(l), fn(r)) for l, r in plan.on]
        filt = fn(plan.filter) if plan.filter is not None else None
        return p.Join(plan.left, plan.right, plan.join_type, on, filt,
                      plan.schema, plan.null_aware)
    if isinstance(plan, p.Aggregate):
        return p.Aggregate(plan.input, [fn(e) for e in plan.group_exprs],
                           [fn(e) for e in plan.agg_exprs], plan.schema)
    if isinstance(plan, p.Sort):
        keys = [replace(k, expr=fn(k.expr)) for k in plan.keys]
        return p.Sort(plan.input, keys, plan.schema, plan.fetch)
    if isinstance(plan, p.Window):
        return p.Window(plan.input, [fn(e) for e in plan.window_exprs], plan.schema)
    if isinstance(plan, p.TableScan) and plan.filters:
        return p.TableScan(plan.schema_name, plan.table_name, plan.schema,
                           plan.projection, [fn(f) for f in plan.filters])
    return plan


class SimplifyExpressions(Rule):
    def apply(self, plan, config, catalog):
        def go(node):
            node = _rewrite_children(node, go)
            return _map_node_exprs(node, simplify_expr)

        return go(plan)


# ---------------------------------------------------------------------------
# EliminateCrossJoin (parity: DataFusion rule; enables TPC-H comma joins)
# ---------------------------------------------------------------------------
class EliminateCrossJoin(Rule):
    def apply(self, plan, config, catalog):
        def go(node):
            node = _rewrite_children(node, go)
            if isinstance(node, p.Filter) and isinstance(node.input, p.CrossJoin):
                cj = node.input
                nleft = len(cj.left.schema)
                on, residual = split_join_condition(node.predicate, nleft)
                if on:
                    join = p.Join(cj.left, cj.right, "INNER", on, None, cj.schema)
                    if residual is not None:
                        return p.Filter(join, residual, join.schema)
                    return join
            if isinstance(node, p.Filter) and isinstance(node.input, p.Join) \
                    and node.input.join_type == "INNER":
                # promote further equi conjuncts into the join keys
                j = node.input
                nleft = len(j.left.schema)
                on, residual = split_join_condition(node.predicate, nleft)
                if on:
                    join = p.Join(j.left, j.right, "INNER", list(j.on) + on,
                                  j.filter, j.schema)
                    if residual is not None:
                        return p.Filter(join, residual, join.schema)
                    return join
            return node

        return go(plan)


# ---------------------------------------------------------------------------
# EliminateLimit
# ---------------------------------------------------------------------------
class EliminateLimit(Rule):
    def apply(self, plan, config, catalog):
        def go(node):
            node = _rewrite_children(node, go)
            if isinstance(node, p.Limit) and node.fetch is None and not node.skip:
                return node.input
            if isinstance(node, p.Limit) and isinstance(node.input, p.Limit):
                inner = node.input
                skip = inner.skip + node.skip
                fetches = [f for f in (
                    None if inner.fetch is None else max(inner.fetch - node.skip, 0),
                    node.fetch) if f is not None]
                fetch = min(fetches) if fetches else None
                return p.Limit(inner.input, skip, fetch, node.schema)
            return node

        return go(plan)


# ---------------------------------------------------------------------------
# PushDownLimit: LIMIT into Sort.fetch / through projections
# ---------------------------------------------------------------------------
class PushDownLimit(Rule):
    def apply(self, plan, config, catalog):
        def go(node):
            node = _rewrite_children(node, go)
            if isinstance(node, p.Limit) and node.fetch is not None:
                want = node.skip + node.fetch
                child = node.input
                if isinstance(child, p.Sort):
                    if child.fetch is None or child.fetch > want:
                        child = p.Sort(child.input, child.keys, child.schema, want)
                        return p.Limit(child, node.skip, node.fetch, node.schema)
                if isinstance(child, p.Projection):
                    pushed = p.Limit(child.input, 0, want, child.input.schema)
                    proj = p.Projection(pushed, child.exprs, child.schema)
                    return p.Limit(proj, node.skip, node.fetch, node.schema)
                if isinstance(child, p.Union) and child.all:
                    kids = [p.Limit(c, 0, want, c.schema) for c in child.children]
                    u = p.Union(kids, True, child.schema)
                    return p.Limit(u, node.skip, node.fetch, node.schema)
            return node

        return go(plan)


# ---------------------------------------------------------------------------
# PushDownFilter
# ---------------------------------------------------------------------------
def _conjuncts(e: Expr) -> List[Expr]:
    if isinstance(e, ScalarFunc) and e.op == "and":
        out = []
        for a in e.args:
            out.extend(_conjuncts(a))
        return out
    return [e]


def _conjoin(parts: List[Expr]) -> Optional[Expr]:
    if not parts:
        return None
    out = parts[0]
    for x in parts[1:]:
        out = ScalarFunc("and", (out, x), SqlType.BOOLEAN)
    return out


def _is_volatile(e: Expr) -> bool:
    return any(isinstance(x, ScalarFunc) and x.op in ("rand", "rand_integer")
               for x in walk(e))


def _has_subquery(e: Expr) -> bool:
    return any(isinstance(x, (ScalarSubqueryExpr, InSubqueryExpr, ExistsExpr))
               for x in walk(e))


class PushDownFilter(Rule):
    def apply(self, plan, config, catalog):
        def go(node):
            node = _rewrite_children(node, go)
            if not isinstance(node, p.Filter):
                return node
            child = node.input
            parts = _conjuncts(node.predicate)

            if isinstance(child, p.Filter):
                merged = _conjoin(parts + _conjuncts(child.predicate))
                return go(p.Filter(child.input, merged, child.schema))

            if isinstance(child, p.Projection):
                pushable, kept = [], []
                for c in parts:
                    if _is_volatile(c) or _has_subquery(c):
                        kept.append(c)
                        continue
                    cols = referenced_columns(c)
                    if all(isinstance(child.exprs[i], (ColumnRef, Literal, Cast,
                                                       ScalarFunc, CaseExpr))
                           and not isinstance(child.exprs[i], AggExpr)
                           for i in cols) and not any(
                               isinstance(child.exprs[i], WindowExpr) or
                               any(isinstance(w, (AggExpr, WindowExpr))
                                   for w in walk(child.exprs[i]))
                               for i in cols):
                        pushable.append(c)
                    else:
                        kept.append(c)
                if pushable:
                    def subst(e):
                        def fn(x):
                            if isinstance(x, ColumnRef) and type(x) is ColumnRef:
                                return child.exprs[x.index]
                            return x
                        return transform(e, fn)

                    pushed_pred = _conjoin([subst(c) for c in pushable])
                    new_input = go(p.Filter(child.input, pushed_pred, child.input.schema))
                    proj = p.Projection(new_input, child.exprs, child.schema)
                    if kept:
                        return p.Filter(proj, _conjoin(kept), child.schema)
                    return proj
                return node

            if isinstance(child, p.SubqueryAlias):
                inner = p.Filter(child.input, node.predicate, child.input.schema)
                return p.SubqueryAlias(go(inner), child.alias, child.schema)

            if isinstance(child, p.Sort):
                inner = go(p.Filter(child.input, node.predicate, child.input.schema))
                return p.Sort(inner, child.keys, child.schema, child.fetch)

            if isinstance(child, (p.Join, p.CrossJoin)):
                nleft = len(child.inputs()[0].schema)
                jt = child.join_type if isinstance(child, p.Join) else "CROSS"
                left_parts, right_parts, kept = [], [], []
                for c in parts:
                    if _is_volatile(c) or _has_subquery(c):
                        kept.append(c)
                        continue
                    cols = referenced_columns(c)
                    if cols and max(cols) < nleft and jt in ("INNER", "LEFT", "CROSS",
                                                            "LEFTSEMI", "LEFTANTI",
                                                            "LEFTMARK"):
                        left_parts.append(c)
                    elif cols and min(cols) >= nleft and jt in ("INNER", "RIGHT", "CROSS"):
                        right_parts.append(shift_columns(c, -nleft))
                    else:
                        kept.append(c)
                if left_parts or right_parts:
                    l, r = child.inputs()
                    if left_parts:
                        l = go(p.Filter(l, _conjoin(left_parts), l.schema))
                    if right_parts:
                        r = go(p.Filter(r, _conjoin(right_parts), r.schema))
                    new_child = child.with_inputs([l, r])
                    if kept:
                        return p.Filter(new_child, _conjoin(kept), node.schema)
                    return new_child
                return node

            if isinstance(child, p.Union):
                kids = [go(p.Filter(c, node.predicate, c.schema)) for c in child.children]
                return p.Union(kids, child.all, child.schema)

            if isinstance(child, p.Aggregate):
                ngroups = len(child.group_exprs)
                pushable, kept = [], []
                for c in parts:
                    cols = referenced_columns(c)
                    if cols and max(cols) < ngroups and not _is_volatile(c) \
                            and not _has_subquery(c):
                        pushable.append(c)
                    else:
                        kept.append(c)
                if pushable:
                    def subst(e):
                        def fn(x):
                            if isinstance(x, ColumnRef) and type(x) is ColumnRef:
                                return child.group_exprs[x.index]
                            return x
                        return transform(e, fn)

                    inner = go(p.Filter(child.input, _conjoin([subst(c) for c in pushable]),
                                        child.input.schema))
                    agg = p.Aggregate(inner, child.group_exprs, child.agg_exprs, child.schema)
                    if kept:
                        return p.Filter(agg, _conjoin(kept), child.schema)
                    return agg
                return node

            if isinstance(child, p.TableScan) and config.get("sql.predicate_pushdown", True):
                ok, kept = [], []
                for c in parts:
                    if _is_volatile(c) or _has_subquery(c):
                        kept.append(c)
                    else:
                        ok.append(c)
                if ok:
                    scan = p.TableScan(child.schema_name, child.table_name, child.schema,
                                       child.projection, list(child.filters) + ok)
                    if kept:
                        return p.Filter(scan, _conjoin(kept), child.schema)
                    return scan
                return node
            return node

        return go(plan)


# ---------------------------------------------------------------------------
# FilterNullJoinKeys: no-op here — the join kernel drops NULL keys natively
# (ops/join.py sentinel gids), which is the semantic this rule protects.
# ---------------------------------------------------------------------------
class FilterNullJoinKeys(Rule):
    def apply(self, plan, config, catalog):
        return plan


# ---------------------------------------------------------------------------
# PushDownProjection: column pruning down to TableScan.projection
# ---------------------------------------------------------------------------
class PushDownProjection(Rule):
    def apply(self, plan, config, catalog):
        required = set(range(len(plan.schema)))
        new_plan, mapping = _prune(plan, required)
        # top level must keep all columns in order
        if mapping != {i: i for i in required}:
            exprs = []
            fields = []
            for i in sorted(required):
                f = plan.schema[i]
                exprs.append(ColumnRef(mapping[i], f.name, f.sql_type, f.nullable))
                fields.append(f)
            return p.Projection(new_plan, exprs, fields)
        return new_plan


def _node_exprs(plan) -> List[Expr]:
    if isinstance(plan, p.Projection):
        return list(plan.exprs)
    if isinstance(plan, p.Filter):
        return [plan.predicate]
    if isinstance(plan, p.Sort):
        return [k.expr for k in plan.keys]
    if isinstance(plan, p.Aggregate):
        return list(plan.group_exprs) + list(plan.agg_exprs)
    if isinstance(plan, p.Window):
        return list(plan.window_exprs)
    if isinstance(plan, p.DistributeBy):
        return list(plan.keys)
    return []


def _prune(plan, required: Set[int]) -> Tuple[p.LogicalPlan, Dict[int, int]]:
    """Prune unused columns bottom-up.  Returns (new_plan, old->new index map)."""
    ident = {i: i for i in range(len(plan.schema))}

    if isinstance(plan, p.TableScan):
        # scan filters may reference pruned columns — those must stay readable
        fcols = set()
        for f in plan.filters:
            fcols |= referenced_columns(f)
        keep = sorted(set(required) | fcols)
        if len(keep) == len(plan.schema) and plan.projection is None:
            return plan, ident
        mapping = {old: new for new, old in enumerate(keep)}
        fields = [plan.schema[i] for i in keep]
        names = [f.name for f in fields]
        filters = [remap_columns(f, mapping) for f in plan.filters]
        scan = p.TableScan(plan.schema_name, plan.table_name, fields, names, filters)
        return scan, mapping

    if isinstance(plan, p.Projection):
        keep = sorted(required)
        child_req = set()
        for i in keep:
            child_req |= referenced_columns(plan.exprs[i])
        new_child, cmap = _prune(plan.input, child_req)
        mapping = {old: new for new, old in enumerate(keep)}
        exprs = [remap_columns(plan.exprs[i], cmap) for i in keep]
        fields = [plan.schema[i] for i in keep]
        return p.Projection(new_child, exprs, fields), mapping

    if isinstance(plan, p.Filter):
        child_req = set(required) | referenced_columns(plan.predicate)
        new_child, cmap = _prune(plan.input, child_req)
        pred = remap_columns(plan.predicate, cmap)
        mapping = {old: cmap[old] for old in child_req}
        f = p.Filter(new_child, pred, list(new_child.schema))
        return f, mapping

    if isinstance(plan, p.Join) and plan.join_type == "LEFTMARK":
        kids = plan.inputs()
        new_kids = [_prune(k, set(range(len(k.schema))))[0] for k in kids]
        if any(a is not b for a, b in zip(kids, new_kids)):
            plan = plan.with_inputs(new_kids)
        return plan, {i: i for i in range(len(plan.schema))}

    if isinstance(plan, p.Join):
        nleft = len(plan.left.schema)
        need = set(required)
        for l, r in plan.on:
            need |= referenced_columns(l) | referenced_columns(r)
        if plan.filter is not None:
            need |= referenced_columns(plan.filter)
        lreq = {i for i in need if i < nleft}
        rreq = {i - nleft for i in need if i >= nleft}
        if plan.join_type in ("LEFTSEMI", "LEFTANTI"):
            pass
        new_left, lmap = _prune(plan.left, lreq)
        new_right, rmap = _prune(plan.right, rreq)
        new_nleft = len(new_left.schema)
        cmap = {}
        for old in lreq:
            cmap[old] = lmap[old]
        for old in rreq:
            cmap[old + nleft] = rmap[old] + new_nleft
        on = [(remap_columns(l, cmap), remap_columns(r, cmap)) for l, r in plan.on]
        filt = remap_columns(plan.filter, cmap) if plan.filter is not None else None
        if plan.join_type in ("LEFTSEMI", "LEFTANTI"):
            fields = list(new_left.schema)
            mapping = {old: lmap[old] for old in required}
        else:
            keep = sorted(cmap)
            fields_all = list(new_left.schema) + list(new_right.schema)
            fields = fields_all
            mapping = {old: cmap[old] for old in required}
        j = p.Join(new_left, new_right, plan.join_type, on, filt, fields,
                   plan.null_aware)
        return j, mapping

    if isinstance(plan, p.CrossJoin):
        nleft = len(plan.left.schema)
        lreq = {i for i in required if i < nleft}
        rreq = {i - nleft for i in required if i >= nleft}
        new_left, lmap = _prune(plan.left, lreq)
        new_right, rmap = _prune(plan.right, rreq)
        new_nleft = len(new_left.schema)
        mapping = {}
        for old in lreq:
            mapping[old] = lmap[old]
        for old in rreq:
            mapping[old + nleft] = rmap[old] + new_nleft
        fields = list(new_left.schema) + list(new_right.schema)
        return p.CrossJoin(new_left, new_right, fields), {o: mapping[o] for o in required}

    if isinstance(plan, p.Aggregate):
        ngroups = len(plan.group_exprs)
        keep_aggs = sorted({i - ngroups for i in required if i >= ngroups})
        child_req = set()
        for g in plan.group_exprs:
            child_req |= referenced_columns(g)
        for i in keep_aggs:
            child_req |= referenced_columns(plan.agg_exprs[i])
        new_child, cmap = _prune(plan.input, child_req)
        groups = [remap_columns(g, cmap) for g in plan.group_exprs]
        aggs = [remap_columns(plan.agg_exprs[i], cmap) for i in keep_aggs]
        fields = ([plan.schema[i] for i in range(ngroups)]
                  + [plan.schema[ngroups + i] for i in keep_aggs])
        mapping = {}
        for i in required:
            if i < ngroups:
                mapping[i] = i
            else:
                mapping[i] = ngroups + keep_aggs.index(i - ngroups)
        return p.Aggregate(new_child, groups, aggs, fields), mapping

    if isinstance(plan, (p.Sort, p.DistributeBy)):
        exprs = _node_exprs(plan)
        child_req = set(required)
        for e in exprs:
            child_req |= referenced_columns(e)
        new_child, cmap = _prune(plan.input, child_req)
        if isinstance(plan, p.Sort):
            keys = [replace(k, expr=remap_columns(k.expr, cmap)) for k in plan.keys]
            fields = list(new_child.schema)
            mapping = {old: cmap[old] for old in required}
            return p.Sort(new_child, keys, fields, plan.fetch), mapping
        keys = [remap_columns(k, cmap) for k in plan.keys]
        mapping = {old: cmap[old] for old in required}
        return p.DistributeBy(new_child, keys, list(new_child.schema)), mapping

    if isinstance(plan, p.Limit):
        new_child, cmap = _prune(plan.input, set(required))
        mapping = {old: cmap[old] for old in required}
        return p.Limit(new_child, plan.skip, plan.fetch, list(new_child.schema)), mapping

    if isinstance(plan, p.SubqueryAlias):
        new_child, cmap = _prune(plan.input, set(required))
        mapping = {old: cmap[old] for old in required}
        return p.SubqueryAlias(new_child, plan.alias,
                               list_fields(plan, new_child, cmap)), mapping

    # default: this node's own schema stays intact, but children still get a
    # pruning pass with full requirements (lets scans below Union/Window/
    # Distinct/Explain drop unused columns via their own chains)
    kids = plan.inputs()
    if kids:
        new_kids = [
            _prune(k, set(range(len(k.schema))))[0] for k in kids
        ]
        if any(a is not b for a, b in zip(kids, new_kids)):
            plan = plan.with_inputs(new_kids)
    return plan, ident


def list_fields(plan, new_child, cmap):
    # SubqueryAlias keeps child schema order; rebuild names from the alias schema
    inv = {v: k for k, v in cmap.items()}
    out = []
    for new_idx in range(len(new_child.schema)):
        old = inv.get(new_idx)
        if old is not None and old < len(plan.schema):
            out.append(plan.schema[old])
        else:
            out.append(new_child.schema[new_idx])
    return out


# ---------------------------------------------------------------------------
# Subquery decorrelation (parity: decorrelate_where_exists.rs / _where_in.rs)
# ---------------------------------------------------------------------------
class DecorrelateSubqueries(Rule):
    def apply(self, plan, config, catalog):
        def go_expr(e: Expr) -> Expr:
            """Recurse into subquery plans embedded in expressions."""
            def fn(x):
                if isinstance(x, (ScalarSubqueryExpr, InSubqueryExpr, ExistsExpr)):
                    from dataclasses import replace as _rp

                    return _rp(x, plan=go(x.plan))
                return x

            return transform(e, fn)

        def go(node):
            node = _rewrite_children(node, go)
            node = _map_node_exprs(node, go_expr)
            if not isinstance(node, p.Filter):
                return node
            # factor common conjuncts out of disjunctions FIRST: q41's
            # correlation hides as (corr AND a) OR (corr AND b), which
            # factors to corr AND (a OR b) — only then is the equality
            # extractable.  (RewriteDisjunctivePredicate can't reach
            # filters inside expr-embedded subquery plans; this walk can.)
            factored = _rewrite_disjunction(node.predicate)
            parts = _conjuncts(factored)
            child = node.input
            orig_width = len(child.schema)
            orig_schema = list(child.schema)
            changed = False
            kept: List[Expr] = []
            for c in parts:
                new_child = self._try_rewrite(c, child)
                if new_child is not None:
                    child = new_child
                    changed = True
                    continue
                res = self._rewrite_scalar(c, child)
                if res is not None:
                    child, new_c = res
                    kept.append(new_c)
                    changed = True
                    continue
                res = self._rewrite_marks(c, child)
                if res is not None:
                    child, new_c = res
                    kept.append(new_c)
                    changed = True
                    continue
                kept.append(c)
            if not changed:
                if factored == node.predicate:
                    return node
                # keep the factored form: the OUTER query's scalar-subquery
                # extraction walks this filter and needs the correlation as
                # its own conjunct
                return p.Filter(child, factored, node.schema)
            out = p.Filter(child, _conjoin(kept), child.schema) if kept else child
            if len(out.schema) != orig_width:
                # scalar rewrites widened the row; project back
                refs = [ColumnRef(i, f.name, f.sql_type, f.nullable)
                        for i, f in enumerate(orig_schema)]
                out = p.Projection(out, refs, orig_schema)
            return out

        return go(plan)

    def _rewrite_scalar(self, conjunct: Expr, child):
        """`expr <op> (SELECT agg FROM ... WHERE inner = outer)` ->
        LEFT join against the per-key aggregated subquery.
        Parity: DataFusion's ScalarSubqueryToJoin in the reference pipeline."""
        subqs = [x for x in walk(conjunct) if isinstance(x, ScalarSubqueryExpr)]
        if len(subqs) != 1:
            return None
        sq = subqs[0]
        node = sq.plan
        while isinstance(node, p.SubqueryAlias):
            node = node.input
        if not isinstance(node, p.Projection) or len(node.exprs) != 1:
            return None
        agg = node.input
        if not isinstance(agg, p.Aggregate) or agg.group_exprs:
            return None
        core = agg.input
        pairs: List[Tuple[Expr, Expr]] = []
        kept: List[Expr] = []
        while isinstance(core, p.Filter):
            for c in _conjuncts(core.predicate):
                pr = _outer_eq_pair(c)
                if pr is not None:
                    pairs.append(pr)
                elif any(isinstance(x, _OuterRef) for x in walk(c)):
                    return None
                else:
                    kept.append(c)
            core = core.input
        if not pairs:
            return None  # uncorrelated: evaluated directly
        for e in _all_exprs_below(core) + list(agg.agg_exprs):
            if any(isinstance(x, _OuterRef) for x in walk(e)):
                return None
        if kept:
            core = p.Filter(core, _conjoin(kept), core.schema)
        key_exprs = [inner for _, inner in pairs]
        ngroups = len(key_exprs)
        agg_fields = ([Field(f"__sckey{i}", e.sql_type, True)
                       for i, e in enumerate(key_exprs)]
                      + [Field(f"__scagg{j}", a.sql_type, True)
                         for j, a in enumerate(agg.agg_exprs)])
        agg2 = p.Aggregate(core, key_exprs, list(agg.agg_exprs), agg_fields)
        # join the RAW aggregates (not the projected expression): the
        # subquery's projection is re-evaluated post-join, where COUNT-like
        # refs get COALESCE(.., 0) — their empty-input value — so unmatched
        # outer rows see COUNT()=0 even inside larger expressions
        # (DataFusion's ScalarSubqueryToJoin behaves the same way).
        naggs = len(agg.agg_exprs)
        sub_fields = ([Field(f"__scagg{j}", a.sql_type, True)
                       for j, a in enumerate(agg.agg_exprs)]
                      + [Field(f"__sckey{i}", e.sql_type, True)
                         for i, e in enumerate(key_exprs)])
        sub_exprs = ([ColumnRef(ngroups + j, f"__scagg{j}", a.sql_type, True)
                      for j, a in enumerate(agg.agg_exprs)]
                     + [ColumnRef(i, f"__sckey{i}", key_exprs[i].sql_type, True)
                        for i in range(ngroups)])
        sub = p.Projection(agg2, sub_exprs, sub_fields)
        nleft = len(child.schema)
        on = [(_outer_to_local(outer),
               ColumnRef(nleft + naggs + i, f"__sckey{i}",
                         key_exprs[i].sql_type, True))
              for i, (outer, _) in enumerate(pairs)]
        join_fields = list(child.schema) + sub_fields
        join = p.Join(child, sub, "LEFT", on, None, join_fields)
        count_like = {"count", "count_star", "regr_count"}

        def remap_agg_ref(x):
            if isinstance(x, ColumnRef):
                j = x.index
                a = agg.agg_exprs[j]
                ref: Expr = ColumnRef(nleft + j, f"__scagg{j}", a.sql_type, True)
                if a.func in count_like:
                    return ScalarFunc("coalesce",
                                      (ref, Literal(0, a.sql_type)), a.sql_type)
                return ref
            return x

        val_expr = transform(node.exprs[0], remap_agg_ref)

        def fn(x):
            if x is sq or x == sq:
                return val_expr
            return x

        new_conjunct = transform(conjunct, fn)
        return join, new_conjunct

    def _rewrite_marks(self, conjunct: Expr, child):
        """Correlated EXISTS that conjunct-wise rewriting can't reach (under
        OR / mixed boolean logic — TPC-DS q10/q35, which the reference
        xfails): each one becomes a MARK JOIN — a semi-join that APPENDS a
        boolean matched column instead of filtering — and the subquery
        expression is replaced by a reference to that column, so the
        disjunction evaluates as ordinary boolean arithmetic.  Returns
        (new_child, rewritten_conjunct) or None."""
        marks = [x for x in walk(conjunct) if isinstance(x, ExistsExpr)
                 and any(isinstance(y, _OuterRef)
                         for e in _all_exprs_below(x.plan) for y in walk(e))]
        if not marks:
            return None
        # plans are immutable, so a mid-loop decline just discards the
        # locally-built chain — no up-front validation pass needed
        replacements: Dict[int, Expr] = {}
        for sub in marks:
            mark_join = self._rewrite_exists(sub, child, anti=False,
                                             mark=True)
            if mark_join is None:
                return None
            nleft = len(child.schema)
            child = mark_join
            ref: Expr = ColumnRef(nleft, "__mark", SqlType.BOOLEAN, False)
            if sub.negated:
                ref = ScalarFunc("not", (ref,), SqlType.BOOLEAN)
            replacements[id(sub)] = ref

        def fn(x):
            return replacements.get(id(x), x)

        return child, transform(conjunct, fn)

    def _try_rewrite(self, pred: Expr, child) -> Optional[p.LogicalPlan]:
        # EXISTS / NOT EXISTS
        if isinstance(pred, ExistsExpr):
            return self._rewrite_exists(pred, child, anti=pred.negated)
        if isinstance(pred, ScalarFunc) and pred.op == "not" \
                and isinstance(pred.args[0], ExistsExpr):
            inner = pred.args[0]
            return self._rewrite_exists(inner, child, anti=not inner.negated)
        # IN subquery (correlated or not)
        if isinstance(pred, InSubqueryExpr):
            return self._rewrite_in(pred, child, anti=pred.negated)
        if isinstance(pred, ScalarFunc) and pred.op == "not" \
                and isinstance(pred.args[0], InSubqueryExpr):
            inner = pred.args[0]
            return self._rewrite_in(inner, child, anti=not inner.negated)
        return None

    def _extract_correlation(self, sub):
        """Decompose the subplan as [Alias?] Projection -> Filter* -> core and
        pull outer-ref conjuncts out of those filters.

        Returns (core_with_residual_filters, proj_exprs, pairs, corr_residuals)
        where proj_exprs / pairs / corr_residuals are bound against the core's
        schema (filters preserve positions); corr_residuals are non-equality
        correlated conjuncts (still containing _OuterRef markers).  Returns
        (None, None, [], []) when the shape doesn't match.
        """
        node = sub
        while isinstance(node, (p.SubqueryAlias, p.Distinct)):
            node = node.inputs()[0]
        if not isinstance(node, p.Projection):
            return None, None, [], []
        proj_exprs = list(node.exprs)
        pairs: List[Tuple[Expr, Expr]] = []
        corr_residuals: List[Expr] = []
        kept: List[Expr] = []
        core = node.input
        while isinstance(core, p.Filter):
            for c in _conjuncts(core.predicate):
                pr = _outer_eq_pair(c)
                if pr is not None:
                    pairs.append(pr)
                elif any(isinstance(x, _OuterRef) for x in walk(c)):
                    if _has_subquery(c):
                        return None, None, [], []
                    corr_residuals.append(c)
                else:
                    kept.append(c)
            core = core.input
        # nothing below the filters may reference the outer query
        for e in _all_exprs_below(core) + proj_exprs:
            if any(isinstance(x, _OuterRef) for x in walk(e)):
                return None, None, [], []
        if kept:
            core = p.Filter(core, _conjoin(kept), core.schema)
        return core, proj_exprs, pairs, corr_residuals

    def _rewrite_exists(self, pred: ExistsExpr, child, anti: bool,
                        mark: bool = False) -> Optional[p.LogicalPlan]:
        core, _, pairs, corr_residuals = self._extract_correlation(pred.plan)
        if core is None or not (pairs or corr_residuals):
            return None  # uncorrelated EXISTS is evaluated directly (cheap)
        nleft = len(child.schema)
        # subquery output := correlation keys + inner columns the residual needs
        key_exprs = [inner for _, inner in pairs]
        resid_inner = sorted({
            x.index for r in corr_residuals for x in walk(r)
            if isinstance(x, ColumnRef) and not isinstance(x, _OuterRef)})
        out_exprs = list(key_exprs) + [
            ColumnRef(i, core.schema[i].name, core.schema[i].sql_type,
                      core.schema[i].nullable) for i in resid_inner]
        fields = [Field(f"__ckey{i}", e.sql_type, True) for i, e in enumerate(out_exprs)]
        sub = p.Projection(core, out_exprs, fields)
        on = [(_outer_to_local(outer), ColumnRef(nleft + i, fields[i].name,
                                                 key_exprs[i].sql_type, True))
              for i, (outer, _) in enumerate(pairs)]
        # residuals: outer refs stay local (< nleft); inner refs point at the
        # projected copies (>= nleft)
        inner_pos = {idx: nleft + len(key_exprs) + j for j, idx in enumerate(resid_inner)}

        def fix_residual(r: Expr) -> Expr:
            def fn(x):
                if isinstance(x, _OuterRef):
                    return ColumnRef(x.index, x.name, x.sql_type, x.nullable)
                if isinstance(x, ColumnRef):
                    from dataclasses import replace as _rp

                    return _rp(x, index=inner_pos[x.index])
                return x

            return transform(r, fn)

        jfilter = _conjoin([fix_residual(r) for r in corr_residuals]) if corr_residuals else None
        if mark:
            fields = list(child.schema) + [Field("__mark", SqlType.BOOLEAN,
                                                 False)]
            return p.Join(child, sub, "LEFTMARK", on, jfilter, fields)
        jt = "LEFTANTI" if anti else "LEFTSEMI"
        return p.Join(child, sub, jt, on, jfilter, list(child.schema))

    def _rewrite_in(self, pred: InSubqueryExpr, child, anti: bool) -> Optional[p.LogicalPlan]:
        core, proj_exprs, pairs, corr_residuals = self._extract_correlation(pred.plan)
        if core is None or corr_residuals:
            return None
        # NOT IN with nullable keys has 3VL semantics a plain anti-join
        # breaks — rewrite to a *null-aware* anti join instead (the physical
        # layer implements the empty-set / NULL-in-set / NULL-arg cases; the
        # reference rewrites this shape in decorrelate_where_in.rs:267)
        null_aware = anti and (pred.plan.schema[0].nullable
                               or _nullable_expr(pred.arg))
        # uncorrelated IN -> semi join below; nullable args need no special
        # handling there (NULL arg rows simply drop, matching WHERE
        # semantics: a NULL predicate filters out)
        nleft = len(child.schema)
        out_exprs = [proj_exprs[0]] + [inner for _, inner in pairs]
        fields = [Field(f"__ckey{i}", e.sql_type, True) for i, e in enumerate(out_exprs)]
        sub = p.Projection(core, out_exprs, fields)
        on = [(pred.arg, ColumnRef(nleft, fields[0].name, out_exprs[0].sql_type, True))]
        for i, (outer, _) in enumerate(pairs):
            on.append((_outer_to_local(outer),
                       ColumnRef(nleft + 1 + i, fields[1 + i].name,
                                 out_exprs[1 + i].sql_type, True)))
        jt = "LEFTANTI" if anti else "LEFTSEMI"
        return p.Join(child, sub, jt, on, None, list(child.schema), null_aware)


class _CannotDecorrelate(Exception):
    pass


def _outer_eq_pair(c: Expr) -> Optional[Tuple[Expr, Expr]]:
    """Match `outer_col = inner_expr` (either side)."""
    if not (isinstance(c, ScalarFunc) and c.op == "eq"):
        return None
    a, b = c.args
    a_outer = all(isinstance(x, _OuterRef) for x in walk(a) if isinstance(x, ColumnRef))
    b_outer = all(isinstance(x, _OuterRef) for x in walk(b) if isinstance(x, ColumnRef))
    a_has = any(isinstance(x, _OuterRef) for x in walk(a))
    b_has = any(isinstance(x, _OuterRef) for x in walk(b))
    if a_has and a_outer and not b_has:
        return (a, b)
    if b_has and b_outer and not a_has:
        return (b, a)
    return None


def _outer_to_local(e: Expr) -> Expr:
    def fn(x):
        if isinstance(x, _OuterRef):
            return ColumnRef(x.index, x.name, x.sql_type, x.nullable)
        return x

    return transform(e, fn)


def _nullable_expr(e: Expr) -> bool:
    for x in walk(e):
        if isinstance(x, ColumnRef) and x.nullable:
            return True
        if isinstance(x, Literal) and x.value is None:
            return True
    return False


def _all_exprs_below(plan) -> List[Expr]:
    out = []
    for node in p.walk_plan(plan):
        out.extend(_node_exprs(node))
    return out


# ---------------------------------------------------------------------------
# UnwrapCastInComparison (parity: DataFusion rule in the reference pipeline,
# optimizer.rs:56,88): CAST(col) <op> literal  ->  col <op> literal-in-col-type
# when the literal round-trips losslessly.  Unwrapped comparisons become
# pushdown-eligible (plain column refs reach the TableScan DNF filters).
# ---------------------------------------------------------------------------
_COMPARISONS = {"eq", "ne", "lt", "le", "gt", "ge"}
_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq", "ne": "ne"}

#: integer widths for the injectivity check
_INT_RANK = {SqlType.TINYINT: 8, SqlType.SMALLINT: 16, SqlType.INTEGER: 32,
             SqlType.BIGINT: 64}
_INT_RANGE = {SqlType.TINYINT: (-2**7, 2**7 - 1),
              SqlType.SMALLINT: (-2**15, 2**15 - 1),
              SqlType.INTEGER: (-2**31, 2**31 - 1),
              SqlType.BIGINT: (-2**63, 2**63 - 1)}


def _cast_is_injective_monotone(src: SqlType, dst: SqlType) -> bool:
    """True only for value-preserving widenings, where
    `CAST(col AS dst) <op> lit`  <=>  `col <op> downcast(lit)` for every col.
    Truncating casts (TIMESTAMP->DATE, DOUBLE->INT, any ->VARCHAR) must NOT
    be unwrapped: they map many column values onto one compared value."""
    if src in _INT_RANK and dst in _INT_RANK:
        return _INT_RANK[src] <= _INT_RANK[dst]
    if src in _INT_RANK and dst == SqlType.DOUBLE:
        return _INT_RANK[src] <= 32  # float64 mantissa covers int32
    if src in _INT_RANK and dst == SqlType.FLOAT:
        return _INT_RANK[src] <= 16  # float32 mantissa covers int16
    if src == SqlType.FLOAT and dst == SqlType.DOUBLE:
        return True
    if src == SqlType.DATE and dst == SqlType.TIMESTAMP:
        return True
    return False


def _try_unwrap_cast(op: str, cast: Cast, lit: Literal):
    from ..binder import _cast_literal

    if lit.value is None:
        return None
    src_type = cast.arg.sql_type
    if not _cast_is_injective_monotone(src_type, cast.sql_type):
        return None
    try:
        down = _cast_literal(Literal(lit.value, lit.sql_type), src_type)
        back = _cast_literal(Literal(down.value, src_type), lit.sql_type)
    except (ArithmeticError, ValueError, TypeError, KeyError):
        return None
    if back.value != lit.value:
        return None  # lossy literal: e.g. 3.5 compared against an INT column
    if src_type in _INT_RANGE:
        lo, hi = _INT_RANGE[src_type]
        try:
            if not (lo <= int(down.value) <= hi):
                return None  # literal overflows the column type
        except (TypeError, ValueError):
            return None
    return ScalarFunc(op, (cast.arg, Literal(down.value, src_type)),
                      SqlType.BOOLEAN)


def _unwrap_cast_expr(e: Expr) -> Expr:
    def fn(x: Expr) -> Expr:
        if isinstance(x, ScalarFunc) and x.op in _COMPARISONS and len(x.args) == 2:
            a, b = x.args
            if isinstance(a, Cast) and isinstance(b, Literal):
                out = _try_unwrap_cast(x.op, a, b)
                if out is not None:
                    return out
            if isinstance(b, Cast) and isinstance(a, Literal):
                out = _try_unwrap_cast(_FLIP[x.op], b, a)
                if out is not None:
                    # keep operand order: literal <op> col == col <flip op> lit
                    return out
        return x

    return transform(e, fn)


class UnwrapCastInComparison(Rule):
    def apply(self, plan, config, catalog):
        def go(node):
            node = _rewrite_children(node, go)
            return _map_node_exprs(node, _unwrap_cast_expr)

        return go(plan)


# ---------------------------------------------------------------------------
# RewriteDisjunctivePredicate (parity: DataFusion rule, optimizer.rs:63):
# (a AND b) OR (a AND c)  ->  a AND (b OR c) — exposes `a` to pushdown.
# ---------------------------------------------------------------------------
def _disjuncts(e: Expr) -> List[Expr]:
    if isinstance(e, ScalarFunc) and e.op == "or":
        out: List[Expr] = []
        for a in e.args:
            out.extend(_disjuncts(a))
        return out
    return [e]


def _disjoin(parts: List[Expr]) -> Expr:
    out = parts[0]
    for x in parts[1:]:
        out = ScalarFunc("or", (out, x), SqlType.BOOLEAN)
    return out


def _rewrite_disjunction(e: Expr) -> Expr:
    def fn(x: Expr) -> Expr:
        if not (isinstance(x, ScalarFunc) and x.op == "or"):
            return x
        branches = [_conjuncts(d) for d in _disjuncts(x)]
        if len(branches) < 2:
            return x
        common = [c for c in branches[0]
                  if all(any(c == c2 for c2 in b) for b in branches[1:])]
        if not common:
            return x
        residuals = []
        for b in branches:
            rem = [c for c in b if not any(c == cm for cm in common)]
            residuals.append(rem)
        if any(not rem for rem in residuals):
            # one branch is exactly the common part: OR collapses to it
            return _conjoin(common)
        parts = common + [_disjoin([_conjoin(rem) for rem in residuals])]
        return _conjoin(parts)

    return transform(e, fn)


class RewriteDisjunctivePredicate(Rule):
    def apply(self, plan, config, catalog):
        def go(node):
            node = _rewrite_children(node, go)
            if isinstance(node, p.Filter):
                return p.Filter(node.input, _rewrite_disjunction(node.predicate),
                                node.schema)
            return node

        return go(plan)


# ---------------------------------------------------------------------------
# EliminateOuterJoin (parity: DataFusion rule, optimizer.rs:70): a filter
# above an outer join that rejects NULLs of the padded side turns the join
# INNER (feeding JoinReorder, which handles inner joins only).
# ---------------------------------------------------------------------------
_NULL_PROP_OPS = _COMPARISONS | {
    "add", "sub", "mul", "div", "mod", "neg", "not", "like", "ilike",
    "similar", "between",
}


def _strong(e: Expr) -> bool:
    """NULL-propagating: any NULL input makes the result NULL."""
    if isinstance(e, (ColumnRef, Literal)):
        return True
    if isinstance(e, Cast):
        return _strong(e.arg)
    if isinstance(e, ScalarFunc) and e.op in _NULL_PROP_OPS:
        return all(_strong(a) for a in e.args)
    return False


def _refs_in_range(e: Expr, lo: int, hi: int) -> bool:
    return any(isinstance(x, ColumnRef) and lo <= x.index < hi for x in walk(e))


def _rejects_nulls(e: Expr, lo: int, hi: int) -> bool:
    """True when `e` cannot evaluate to TRUE if all columns in [lo, hi)
    are NULL (so the filter drops the outer join's padded rows)."""
    if isinstance(e, ScalarFunc):
        if e.op == "and":
            return any(_rejects_nulls(a, lo, hi) for a in e.args)
        if e.op == "or":
            return all(_rejects_nulls(a, lo, hi) for a in e.args)
        if e.op in ("is_not_null", "isnotnull"):
            return _strong(e.args[0]) and _refs_in_range(e.args[0], lo, hi)
        if e.op in _NULL_PROP_OPS:
            return (all(_strong(a) for a in e.args)
                    and _refs_in_range(e, lo, hi))
    return False


class EliminateOuterJoin(Rule):
    def apply(self, plan, config, catalog):
        def go(node):
            node = _rewrite_children(node, go)
            if not (isinstance(node, p.Filter) and isinstance(node.input, p.Join)):
                return node
            join = node.input
            if join.join_type not in ("LEFT", "RIGHT", "FULL"):
                return node
            nleft = len(join.left.schema)
            total = len(join.schema)
            rej_left = rej_right = False
            for c in _conjuncts(node.predicate):
                rej_left = rej_left or _rejects_nulls(c, 0, nleft)
                rej_right = rej_right or _rejects_nulls(c, nleft, total)
            jt = join.join_type
            new_jt = None
            if jt == "LEFT" and rej_right:
                new_jt = "INNER"
            elif jt == "RIGHT" and rej_left:
                new_jt = "INNER"
            elif jt == "FULL":
                # rej_left drops the rows whose LEFT side is padded — the
                # unmatched-right rows — leaving a LEFT join (and vice versa)
                if rej_left and rej_right:
                    new_jt = "INNER"
                elif rej_left:
                    new_jt = "LEFT"
                elif rej_right:
                    new_jt = "RIGHT"
            if new_jt is None:
                return node
            new_join = p.Join(join.left, join.right, new_jt, join.on,
                              join.filter, join.schema, join.null_aware)
            return p.Filter(new_join, node.predicate, node.schema)

        return go(plan)
