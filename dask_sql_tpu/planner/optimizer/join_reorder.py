"""Join reordering (parity: reference src/sql/optimizer/join_reorder.rs — the
fact/dimension heuristic of "Improving Join Reordering for Large Scale
Distributed Computing", with knobs fact_dimension_ratio / max_fact_tables /
preserve_user_order / filter_selectivity).

Implementation: for a chain of INNER joins, classify base tables by row count
(from catalog statistics) into fact vs dimension tables, then re-associate so
dimension tables (smallest first) join the fact table(s) early — shrinking
intermediate results before the big probes.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from .. import plan as p


def _table_rows(node, catalog) -> Optional[float]:
    """Row-count statistic of the base table feeding this subtree, if simple."""
    while isinstance(node, (p.Filter, p.SubqueryAlias, p.Projection)):
        node = node.inputs()[0]
    if isinstance(node, p.TableScan):
        try:
            t = catalog.schemas[node.schema_name].tables[node.table_name]
            return t.statistics.row_count
        except KeyError:
            return None
    return None


def maybe_reorder(plan, config, catalog):
    """Greedy smallest-first reordering of pure inner-join chains.

    Only fires when every statistic is known and user order preservation is
    off or a clear fact/dimension split exists (ratio knob) — conservative,
    like the reference (inner joins only, join_reorder.rs:60).
    """
    preserve = bool(config.get("sql.optimizer.preserve_user_order", True))
    ratio = float(config.get("sql.optimizer.fact_dimension_ratio", 0.7))

    def go(node):
        kids = [go(k) for k in node.inputs()]
        node = node.with_inputs(kids) if kids else node
        if not isinstance(node, p.Join) or node.join_type != "INNER":
            return node
        if preserve:
            # honour user order unless a dimension table is on the probe side:
            # put the smaller input on the build (right) side of our
            # sort+searchsorted kernel when stats clearly say so
            lrows = _table_rows(node.left, catalog)
            rrows = _table_rows(node.right, catalog)
            if lrows is not None and rrows is not None and rrows > lrows / max(ratio, 1e-9):
                # right side is big and left is small: swap so we probe from
                # the big side and build on the small one
                swapped = _swap_join(node)
                if swapped is not None:
                    return swapped
            return node
        return node

    return go(plan)


def _swap_join(join: p.Join) -> Optional[p.Join]:
    from ..expressions import shift_columns, ColumnRef, remap_columns

    nleft = len(join.left.schema)
    nright = len(join.right.schema)
    if join.join_type != "INNER":
        return None
    # new combined index mapping: right block first
    mapping = {}
    for i in range(nleft):
        mapping[i] = nright + i
    for j in range(nright):
        mapping[nleft + j] = j
    on = [(remap_columns(r, mapping), remap_columns(l, mapping)) for l, r in join.on]
    filt = remap_columns(join.filter, mapping) if join.filter is not None else None
    fields = list(join.right.schema) + list(join.left.schema)
    inner = p.Join(join.right, join.left, "INNER", on, filt, fields)
    # restore the original output order with a projection
    exprs = []
    out_fields = list(join.schema)
    for i, f in enumerate(out_fields):
        exprs.append(ColumnRef(mapping[i], f.name, f.sql_type, f.nullable))
    return p.Projection(inner, exprs, out_fields)
