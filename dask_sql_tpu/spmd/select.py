"""spmd_select: the sharded compiled rung for ROOT select chains.

`scan -> filter* -> project [limit]` over a mesh-sharded table compiles to
TWO shard_map SPMD programs sharing the single-chip `CompiledSelect` traced
bodies: the mask kernel evaluates the selection per shard (pad rows masked
by `row_valid`) and returns the sharded mask plus per-shard survivor
counts; the gather kernel compacts each shard's survivors into a static
power-of-two bucket and packs them into one f64 matrix whose device axis is
the mesh — the host pulls it in ONE transfer sized by the largest shard's
survivors, slices each shard's real rows, and concatenates in global row
order (row-block sharding is contiguous, sized-nonzero indices ascend).

Declines: ORDER BY chains only (the range-partition `dist_sort` keeps
results sharded in sort order — pulling everything to one host would
defeat that layout).  Inner LIMIT windows ARE supported: the survivor
ordinal the window slices stays a GLOBAL row ordinal via an
all_gather-prefix override of `_survivor_ordinal`.  ParamRefs stay traced
runtime arguments — one SPMD executable per family, zero foreground
compiles for the second literal variant — and the family batcher's
stacked launches vmap the mask program over the parameter axis.
"""
from __future__ import annotations

import logging
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from ..columnar.table import Table
from ..parallel.mesh import AXIS
from ..physical.compiled import (
    _Unsupported,
    defer_rebuild,
    singleflight_get_or_build,
)
from ..physical.compiled_select import CompiledSelect, _extract
from .core import ColumnSpmdWrap, mesh_key, mesh_of_sharded_table, rung_enabled

logger = logging.getLogger(__name__)


class SpmdSelect(CompiledSelect):
    #: False while the single-chip eval_shape eligibility trace runs in
    #: __init__ (no mesh axis bound there); flipped once construction
    #: finishes so shard_map traces take the cross-shard ordinal
    _use_global_ordinal = False

    def __init__(self, mesh, table, scan, upper_filters, scan_filters,
                 proj, proj_exprs, sort_keys, sort_fetch, limit, inner_limit,
                 params=()):
        if sort_keys is not None:
            raise _Unsupported("spmd select keeps ORDER BY on dist_sort")
        super().__init__(table, scan, upper_filters, scan_filters, proj,
                         proj_exprs, sort_keys, sort_fetch, limit,
                         inner_limit, params)
        self._use_global_ordinal = True
        self.mesh = mesh
        self.ndev = int(mesh.devices.size)
        names = table.column_names
        self._valid_present = tuple(table.columns[n].validity is not None
                                    for n in names)
        self._has_row_valid = table.row_valid is not None

        def mask_shard(datas, valids, row_valid, params):
            mask, cnt = self._mask_fn_raw(datas, valids, row_valid, params)
            return mask, cnt[None]  # per-shard survivor count -> [ndev]

        self._mask_wraps: Dict[int, ColumnSpmdWrap] = {}
        self._mask_shard = mask_shard
        #: per pow2 bucket: jitted shard_map gather (out [R, ndev*bucket])
        self._spmd_gathers: Dict[Tuple[int, int], object] = {}
        self._mask_batched_jit = None

    def _survivor_ordinal(self, mask):
        """Global survivor ordinal under shard_map: local cumsum plus the
        all-gathered prefix of lower-indexed shards' totals, so an inner
        LIMIT window (PushDownLimit parks limits right above the scan)
        keeps its single-chip semantics — the window is a prefix of the
        GLOBAL survivor sequence, not a per-shard one."""
        import jax.numpy as jnp

        local = jnp.cumsum(mask.astype(jnp.int64))
        if not self._use_global_ordinal:
            return local
        total = local[-1] if mask.shape[0] else jnp.int64(0)
        totals = jax.lax.all_gather(total, AXIS)  # [ndev]
        me = jax.lax.axis_index(AXIS)
        offset = jnp.sum(jnp.where(
            jnp.arange(totals.shape[0]) < me, totals, 0))
        return local + offset

    # ------------------------------------------------------------- wrappers
    def _mask_wrap(self, n_params: int) -> ColumnSpmdWrap:
        w = self._mask_wraps.get(n_params)
        if w is None:
            w = ColumnSpmdWrap(
                self._mask_shard, self.mesh, self._valid_present,
                self._has_row_valid, n_params,
                out_specs=(P(AXIS), P(AXIS)), check_rep=False)
            self._mask_wraps[n_params] = w
        return w

    def _gather_mapped(self, bucket: int, n_params: int):
        key = (bucket, n_params)
        fn = self._spmd_gathers.get(key)
        if fn is None:
            raw = self._gather_fn_raw

            def gather_shard(datas, valids, mask, params):
                # the mask rides the row_valid slot of the generic wrap
                # (same row-block spec); the raw single-chip gather body
                # compacts this shard's survivors into the static bucket
                return raw(datas, valids, mask, params, bucket)

            w = ColumnSpmdWrap(gather_shard, self.mesh, self._valid_present,
                               True, n_params,
                               out_specs=P(None, AXIS), check_rep=False)
            fn = (w, w.jitted)
            self._spmd_gathers[key] = fn
        return fn

    # ------------------------------------------------------------ execution
    def run(self, table: Optional[Table] = None, params: Tuple = ()) -> Table:
        from ..observability import timed_jit_call
        from ..utils import count_d2h

        t = table if table is not None else self.table
        datas = [t.columns[n].data for n in t.column_names]
        valids = [t.columns[n].validity for n in t.column_names]
        wrap = self._mask_wrap(len(params))
        args = wrap.pack_args(datas, valids, t.row_valid, params)
        mask, counts = timed_jit_call("spmd_select", wrap.jitted, *args,
                                      may_compile=not self._mask_warm)
        self._mask_warm = True
        count_d2h()
        counts_h = np.asarray(jax.device_get(counts)).astype(np.int64)
        return self._finish_spmd(datas, valids, mask, counts_h, params)

    def run_batched(self, table: Table, params_list: List[Tuple]
                    ) -> List[Table]:
        """ONE vmapped SPMD mask launch for every co-admitted member over a
        single sharded scan; per-member survivor gathers share the
        per-bucket SPMD gather executables."""
        from ..families import stack_params
        from ..observability import timed_jit_call
        from ..utils import count_d2h

        n = len(params_list)
        stacked, bucket = stack_params(params_list)
        wrap = self._mask_wrap(len(params_list[0]))
        if self._mask_batched_jit is None:
            self._mask_batched_jit = jax.jit(
                jax.vmap(wrap.mapped, in_axes=(None, None, None, 0)))
        datas = [table.columns[c].data for c in table.column_names]
        valids = [table.columns[c].validity for c in table.column_names]
        args = wrap.pack_args(datas, valids, table.row_valid, stacked)
        masks, counts = timed_jit_call(
            "spmd_select", self._mask_batched_jit, *args,
            may_compile=bucket not in self._warm_mask_batch)
        self._warm_mask_batch.add(bucket)
        count_d2h()
        counts_h = np.asarray(jax.device_get(counts)).astype(np.int64)
        return [self._finish_spmd(datas, valids, masks[b], counts_h[b],
                                  params_list[b]) for b in range(n)]

    def _finish_spmd(self, datas, valids, mask, counts_h: np.ndarray,
                     params: Tuple) -> Table:
        from ..observability import timed_jit_call
        from ..utils import count_d2h

        total = int(counts_h.sum())
        want = self._limit_trim(total)
        if want < total:
            # sort-free LIMIT: survivors ascend in global row order, so the
            # window is a prefix across shards in mesh order
            before = np.concatenate(([0], np.cumsum(counts_h)[:-1]))
            take = np.clip(want - before, 0, counts_h)
        else:
            take = counts_h
        count = int(take.sum())
        if count == 0:
            cols, valid_arrs = self._decode_packed(None, 0)
            return self._assemble(cols, valid_arrs, 0)
        bucket = 1 << (int(take.max()) - 1).bit_length()
        wrap, gfn = self._gather_mapped(bucket, len(params))
        args = wrap.pack_args(datas, valids, mask, params)
        packed = timed_jit_call("spmd_select", gfn, *args,
                                may_compile=bucket not in self._warm_buckets)
        self._warm_buckets.add(bucket)
        count_d2h()
        host_all = np.asarray(jax.device_get(packed))  # [R, ndev*bucket]
        parts = [host_all[:, d * bucket: d * bucket + int(take[d])]
                 for d in range(self.ndev) if take[d]]
        host = np.concatenate(parts, axis=1) if parts else None
        cols, valid_arrs = self._decode_packed(host, count)
        return self._assemble(cols, valid_arrs, count)


_CACHE_CAP = 16
_cache: "OrderedDict[Tuple, SpmdSelect]" = OrderedDict()


def _family_of(key: Tuple) -> Tuple:
    # drop table identity: uid (index 2) and the trailing row buckets
    return key[:2] + key[3:-2]


def _bucket_of(key: Tuple) -> Tuple:
    return (key[2], key[-2], key[-1])  # (uid, num_rows, padded_rows)


def _defer_to_background(ctx, mesh, key, table, scan, p_upper, p_scan_flts,
                         proj, p_exprs, limit, inner_limit, params) -> bool:
    """Background-recompile hook for SPMD root select chains — the shared
    `defer_rebuild` policy (physical/compiled.py) with this rung's
    constructor; True = deferred."""

    def build_and_warm():
        obj = SpmdSelect(mesh, table, scan, p_upper, p_scan_flts, proj,
                         p_exprs, None, None, limit, inner_limit, params)
        obj.run(table, params)  # compiles mask + first gather
        obj.table = None
        return obj

    return defer_rebuild(ctx, "spmd_select", _cache, _CACHE_CAP, key,
                         _family_of(key), _bucket_of(key), build_and_warm)


def try_spmd_select(root, executor) -> Optional[Table]:
    """Attempt the SPMD root-select path over a mesh-sharded scan; None
    steps down (compiled_select declines sharded tables, so the next
    answering rung is typically the interpreted walk)."""
    if not executor.config.get("sql.compile", True) \
            or not executor.config.get("sql.compile.select", True):
        return None
    if not rung_enabled(executor.config, "spmd_select"):
        return None
    got = _extract(root)
    if got is None:
        return None
    scan, upper_filters, proj, sort_keys, sort_fetch, limit, inner_limit = got
    if sort_keys is not None:
        return None  # ORDER BY keeps the dist_sort sharded layout
    try:
        ctx = executor.context
        from ..datacontainer import LazyParquetContainer

        dc = ctx.schema[scan.schema_name].tables.get(scan.table_name)
        if dc is None or isinstance(dc, LazyParquetContainer):
            return None
        table = executor.get_table(scan.schema_name, scan.table_name)
        if scan.projection is not None:
            table = table.select(scan.projection)
        if not table.column_names:
            return None
        mesh = mesh_of_sharded_table(table)
        if mesh is None:
            return None
        from .. import families

        pz = families.pipeline_parameterizer(executor.config)
        p_upper = [pz.rewrite(f) for f in upper_filters]
        p_scan_flts = [pz.rewrite(f) for f in scan.filters]
        p_exprs = [pz.rewrite(e) for e in proj.exprs]
        params = pz.params
        key = (
            "spmd_select",
            mesh_key(mesh),
            dc.uid,
            # table NAME stays in the family (only the uid is table-version
            # identity): same-shaped queries over different tables must not
            # collide in the background-recompile family map
            scan.schema_name, scan.table_name,
            tuple(scan.projection or ()),
            tuple(str(f) for f in p_upper),
            tuple(str(f) for f in p_scan_flts),
            tuple(str(e) for e in p_exprs),
            limit,
            inner_limit,
            table.num_rows,
            table.padded_rows,
        )

        def build():
            if _defer_to_background(ctx, mesh, key, table, scan, p_upper,
                                    p_scan_flts, proj, p_exprs, limit,
                                    inner_limit, params):
                return None  # served on a lower rung this time
            from ..physical.compiled import _remember_family_locked

            obj = SpmdSelect(mesh, table, scan, p_upper, p_scan_flts, proj,
                             p_exprs, sort_keys, sort_fetch, limit,
                             inner_limit, params)
            obj.table = None
            with ctx._plan_lock:
                _cache[key] = obj
                while len(_cache) > _CACHE_CAP:
                    _cache.popitem(last=False)
                _remember_family_locked(ctx, _family_of(key),
                                        _bucket_of(key))
            return obj

        compiled, built_here = singleflight_get_or_build(ctx, _cache, key,
                                                         build)
        if compiled is None:
            return None
        if not built_here and params:
            ctx.metrics.inc("families.hit")
            from ..observability import trace_event

            trace_event("family_hit", rung="spmd_select", params=len(params))
        ctx.metrics.inc("parallel.spmd.launches")
        ctx.metrics.inc("parallel.spmd.rows", table.num_rows)
        from ..resilience import faults

        faults.maybe_inject("oom", executor.config)
        batcher = families.batcher_of(ctx)
        if batcher is not None and params:
            return batcher.run(
                key, params,
                solo=lambda: compiled.run(table, params),
                batched=lambda members: compiled.run_batched(table, members))
        return compiled.run(table, params)
    except _Unsupported as e:
        logger.debug("spmd select unsupported: %s", e)
        return None
    except (ValueError, TypeError, NotImplementedError) as e:
        logger.debug("spmd select declined: %s", e)
        return None
