"""Equijoin kernels: sort + searchsorted, TPU-first.

Replaces the reference's dask hash-shuffle merge (join.py:241-246 there) for
the single-device path: both sides' keys are jointly factorized to dense ints
(`grouping.factorize` over the concatenation), the right side is sorted once,
and each left row finds its match range via two `searchsorted`s — O((n+m) log m)
in fully-vectorized XLA ops, no host hash tables.  Match expansion uses
data-dependent shapes (eager dispatch), which is fine outside jit; the
distributed path shuffles with collectives first (parallel/shuffle.py) and
then runs this same kernel per shard.

NULL semantics: SQL equijoin keys never match NULL (reference join.py:202-213
filters NULL keys); invalid rows get sentinel gids (-1 left, -2 right).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.column import Column
from ..columnar.dtypes import STRING_TYPES, promote
from .grouping import factorize
from ..utils import host_ints


def _merge_string_dicts(lcol: Column, rcol: Column) -> Tuple[jnp.ndarray, jnp.ndarray]:
    ld = lcol.dictionary if lcol.dictionary is not None else np.array([""], dtype=object)
    rd = rcol.dictionary if rcol.dictionary is not None else np.array([""], dtype=object)
    merged = np.unique(np.concatenate([ld.astype(str), rd.astype(str)]))
    lmap = jnp.asarray(np.searchsorted(merged, ld.astype(str)).astype(np.int32))
    rmap = jnp.asarray(np.searchsorted(merged, rd.astype(str)).astype(np.int32))
    lk = lmap[jnp.clip(lcol.data, 0, len(ld) - 1)]
    rk = rmap[jnp.clip(rcol.data, 0, len(rd) - 1)]
    return lk, rk


def join_key_gids(
    left_keys: Sequence[Column], right_keys: Sequence[Column],
    null_equals_null: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Jointly factorize both sides' key columns into comparable dense ints.

    `null_equals_null=True` gives IS NOT DISTINCT FROM matching (set ops);
    the default is SQL equijoin semantics where NULL matches nothing.
    """
    nl = len(left_keys[0]) if left_keys else 0
    nr = len(right_keys[0]) if right_keys else 0
    if len(left_keys) == 1 and not null_equals_null:
        fast = _single_key_fast_path(left_keys[0], right_keys[0])
        if fast is not None:
            return fast
    combined: List[jnp.ndarray] = []
    for lc, rc in zip(left_keys, right_keys):
        if lc.sql_type in STRING_TYPES or rc.sql_type in STRING_TYPES:
            lk, rk = _merge_string_dicts(lc, rc)
        else:
            target = promote(lc.sql_type, rc.sql_type)
            lk = lc.cast(target).data
            rk = rc.cast(target).data
        k = jnp.concatenate([lk, rk])
        if null_equals_null and (lc.validity is not None or rc.validity is not None):
            # NULL == NULL matching: validity becomes part of the key and the
            # payload is zeroed under NULL so all NULLs collide
            v = jnp.concatenate([lc.valid_mask(), rc.valid_mask()])
            combined.append(jnp.where(v, k, jnp.zeros_like(k)))
            combined.append(v.astype(jnp.int32))
        else:
            combined.append(k)
    gid, _, _ = factorize(combined)
    lgid, rgid = gid[:nl], gid[nl:]
    if null_equals_null:
        return lgid.astype(jnp.int64), rgid.astype(jnp.int64)
    # NULL keys never match
    lvalid = jnp.ones(nl, dtype=bool)
    for c in left_keys:
        if c.validity is not None:
            lvalid &= c.valid_mask()
    rvalid = jnp.ones(nr, dtype=bool)
    for c in right_keys:
        if c.validity is not None:
            rvalid &= c.valid_mask()
    lgid = jnp.where(lvalid, lgid, -1)
    rgid = jnp.where(rvalid, rgid, -2)
    return lgid.astype(jnp.int64), rgid.astype(jnp.int64)


def _single_key_fast_path(lc: Column, rc: Column):
    """Single integer/datetime key: the values themselves are the join ids —
    no joint factorization lexsort needed (the dominant cost for big probes).
    NULL sentinels use int64 extremes, which real key values never hit."""
    if lc.sql_type in STRING_TYPES or rc.sql_type in STRING_TYPES:
        lk, rk = _merge_string_dicts(lc, rc)
        lk = lk.astype(jnp.int64)
        rk = rk.astype(jnp.int64)
    else:
        target = promote(lc.sql_type, rc.sql_type)
        lk = lc.cast(target).data
        rk = rc.cast(target).data
        if not jnp.issubdtype(lk.dtype, jnp.integer):
            return None  # float keys keep the exact factorize path
        lk = lk.astype(jnp.int64)
        rk = rk.astype(jnp.int64)
    lo = jnp.iinfo(jnp.int64).min
    if lc.validity is not None or rc.validity is not None:
        # sentinel safety: real keys must not collide with the NULL
        # sentinels — both mins ride one device pull
        mins = host_ints(*([jnp.min(lk)] if lk.shape[0] else []),
                         *([jnp.min(rk)] if rk.shape[0] else []))
        if any(m <= lo + 1 for m in mins):
            return None
        if lc.validity is not None:
            lk = jnp.where(lc.valid_mask(), lk, lo)  # never matches rhs sentinel
        if rc.validity is not None:
            rk = jnp.where(rc.valid_mask(), rk, lo + 1)
    return lk, rk


# LUT join: cap the value range at a small multiple of the build side so the
# scatter table stays HBM-friendly (TPC-H orderkeys are 4x-sparse, hence 8x)
_DENSE_RANGE_SLACK = 8
_DENSE_RANGE_FLOOR = 1 << 16


@jax.jit
def _minmax(x):
    return jnp.min(x), jnp.max(x)


def _dense_match(lgid, rgid):
    """Unique-dense-int build side: per-left-row (matched, right_idx) in
    O(n) scatter/gather, no sort.  None when ineligible.

    The reference leans on pandas' hash join (join.py:241-246 there); on
    XLA the natural analogue of a hash table is a value-indexed LUT — a
    single scatter + gather that the TPU does at HBM bandwidth, vs the
    O(n log n) argsort of the general probe.

    NULL sentinels need no special casing: the factorized-gid encoding uses
    -1 (left) / -2 (right) against non-negative real gids, so a NULL slot in
    the LUT can never be probed by a real key; the raw single-key encoding
    uses int64 extremes, which blow the range gate and fall back to the
    sort path (only when NULLs are actually present — see join_key_gids)."""
    nr = int(rgid.shape[0])
    if nr == 0 or lgid.shape[0] == 0:
        return None
    rmin, rmax = host_ints(*_minmax(rgid))
    size = rmax - rmin + 1
    if size <= 0 or size > max(_DENSE_RANGE_SLACK * nr, _DENSE_RANGE_FLOOR):
        return None
    idx = rgid - rmin
    counts = jnp.zeros(size, dtype=jnp.int32).at[idx].add(1)
    if int(jnp.max(counts)) > 1:
        return None
    lut = jnp.full(size, -1, dtype=jnp.int64)
    lut = lut.at[idx].set(jnp.arange(nr, dtype=jnp.int64))
    pidx = lgid - rmin
    inb = (pidx >= 0) & (pidx < size)
    ri_cand = jnp.where(inb, lut[jnp.clip(pidx, 0, size - 1)], -1)
    matched = ri_cand >= 0
    return matched, ri_cand


def dense_unique_lut(key: jnp.ndarray, valid=None):
    """(rmin, lut) for a unique-dense-int key column, or None if ineligible.

    lut[v - rmin] = row index holding key v, -1 where no row does.  NULL
    rows (valid=False) never enter the table.  Shares the eligibility rules
    of _dense_match; used by the compiled join pipeline, which builds LUTs
    eagerly per build table and probes inside one jit."""
    nr = int(key.shape[0])
    if nr == 0 or not jnp.issubdtype(key.dtype, jnp.integer):
        return None
    k = key.astype(jnp.int64)
    if valid is not None:
        # exclude NULLs from the range scan so they can't blow the gate
        big = jnp.iinfo(jnp.int64).max
        small = jnp.iinfo(jnp.int64).min
        rmin, rmax = host_ints(jnp.min(jnp.where(valid, k, big)),
                               jnp.max(jnp.where(valid, k, small)))
        if rmin > rmax:
            return None  # all NULL
    else:
        rmin, rmax = host_ints(*_minmax(k))
    size = rmax - rmin + 1
    if size <= 0 or size > max(_DENSE_RANGE_SLACK * nr, _DENSE_RANGE_FLOOR):
        return None
    idx = k - rmin
    if valid is not None:
        idx = jnp.where(valid, idx, size)  # out of bounds -> dropped
    counts = jnp.zeros(size, dtype=jnp.int32).at[idx].add(1, mode="drop")
    if int(jnp.max(counts)) > 1:
        return None
    # row ids always fit int32 (single-shard row counts < 2^31); int64
    # gathers/compares are emulated on TPU
    lut = jnp.full(size, -1, dtype=jnp.int32)
    lut = lut.at[idx].set(jnp.arange(nr, dtype=jnp.int32), mode="drop")
    return rmin, lut


def inner_join_indices(lgid: jnp.ndarray, rgid: jnp.ndarray,
                       use_jit: bool = False) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(left_idx, right_idx) pairs of matches, left-major order."""
    dense = _dense_match(lgid, rgid)
    if dense is not None:
        matched, ri_cand = dense
        li = jnp.nonzero(matched)[0].astype(jnp.int64)
        return li, ri_cand[li]
    li, ri, _ = _probe(lgid, rgid, use_jit)
    return li, ri


def left_join_indices(lgid, rgid, use_jit: bool = False):
    """Left outer: unmatched left rows appear once with right_idx == -1."""
    dense = _dense_match(lgid, rgid)
    if dense is not None:
        # unique build keys: every left row appears exactly once
        matched, ri_cand = dense
        li = jnp.arange(lgid.shape[0], dtype=jnp.int64)
        return li, jnp.where(matched, ri_cand, -1)
    phase = _probe_phase_jit if use_jit else _probe_phase
    r_order, start, counts, _, _ = phase(lgid, rgid)
    out_counts = jnp.maximum(counts, 1)
    total = int(out_counts.sum())
    offsets = jnp.cumsum(out_counts) - out_counts  # exclusive prefix
    li = jnp.repeat(jnp.arange(lgid.shape[0], dtype=jnp.int64), out_counts,
                    total_repeat_length=total)
    pos_in_row = jnp.arange(total, dtype=jnp.int64) - offsets[li]
    matched = counts[li] > 0
    ri_raw = r_order[jnp.clip(start[li] + pos_in_row, 0, max(rgid.shape[0] - 1, 0))]
    ri = jnp.where(matched, ri_raw, -1)
    return li, ri


def semi_join_mask(lgid, rgid, anti: bool = False) -> jnp.ndarray:
    dense = _dense_match(lgid, rgid)
    if dense is not None:
        matched, _ = dense
        return ~matched if anti else matched
    r_sorted = jnp.sort(rgid)
    start = jnp.searchsorted(r_sorted, lgid, side="left")
    end = jnp.searchsorted(r_sorted, lgid, side="right")
    matched = (end - start) > 0
    return ~matched if anti else matched


def full_join_indices(lgid, rgid, use_jit: bool = False):
    li, ri = left_join_indices(lgid, rgid, use_jit)
    r_unmatched = ~semi_join_mask(rgid, lgid)
    extra_r = jnp.nonzero(r_unmatched)[0].astype(jnp.int64)
    li = jnp.concatenate([li, jnp.full(extra_r.shape[0], -1, dtype=jnp.int64)])
    ri = jnp.concatenate([ri, extra_r])
    return li, ri


def _probe_phase(lgid, rgid):
    """Shape-stable probe phase: sort, two binary searches, prefix sums.

    Everything up to the data-dependent expansion is static-shaped, so the
    jitted variant compiles once per (n_l, n_r) signature — removing per-op
    dispatch round trips, which dominate when the device sits behind a link
    (TPU).  Selected via `sql.compile.join`.
    """
    r_order = jnp.argsort(rgid)
    r_sorted = rgid[r_order]
    start = jnp.searchsorted(r_sorted, lgid, side="left")
    end = jnp.searchsorted(r_sorted, lgid, side="right")
    counts = end - start
    offsets = jnp.cumsum(counts) - counts
    total = counts.sum()
    return r_order, start, counts, offsets, total


_probe_phase_jit = jax.jit(_probe_phase)


def _probe(lgid, rgid, use_jit: bool = False):
    phase = _probe_phase_jit if use_jit else _probe_phase
    r_order, start, counts, offsets, total_arr = phase(lgid, rgid)
    total = int(total_arr)
    li = jnp.repeat(jnp.arange(lgid.shape[0], dtype=jnp.int64), counts,
                    total_repeat_length=total)
    pos_in_row = jnp.arange(total, dtype=jnp.int64) - offsets[li]
    ri = r_order[start[li] + pos_in_row]
    return li, ri, counts


def take_with_nulls(col: Column, indices: jnp.ndarray,
                    may_pad: Optional[bool] = None) -> Column:
    """Gather rows; index -1 produces NULL (outer-join fill).

    `may_pad` tells the gather statically whether -1 fills can occur
    (False for inner/semi matches, True for outer padding) — without it a
    per-column content check costs a device round trip per column."""
    n = len(col)
    if n == 0:
        # empty source: every index is the -1 fill (outer join against an
        # empty side, TPC-DS q77) — an all-NULL column of the output length
        m = int(indices.shape[0])
        return Column(jnp.zeros(m, dtype=col.data.dtype), col.sql_type,
                      jnp.zeros(m, dtype=bool), col.dictionary)
    neg = indices < 0
    if may_pad is False and __debug__:
        # contract check: may_pad=False promises no -1 fills, and a violation
        # silently materializes clamped garbage rows marked valid.  The
        # device sync is only paid when the validation flag is on.
        from .. import config as config_module

        if config_module.get("sql.debug.validate_take", False):
            assert not bool(neg.any()), (
                "take_with_nulls(may_pad=False) received negative indices; "
                "the calling join type must pass may_pad=True")
    safe = jnp.clip(indices, 0, max(n - 1, 0))
    data = col.data[safe]
    if may_pad is None:
        may_pad = bool(neg.any())
    if not may_pad and col.validity is None:
        return Column(data, col.sql_type, None, col.dictionary)
    valid = col.valid_mask()[safe] & ~neg
    return Column(data, col.sql_type, valid, col.dictionary)
