"""Serving runtime: the layer between the front-ends (Presto server,
`Context.sql`) and the executor for multi-query traffic.

Three cooperating parts (TCR, arXiv:2203.01877 — once kernels are XLA-bound,
end-to-end serving wins come from the runtime around them; Flare,
arXiv:1703.08219 makes the same point for compiled Spark):

- :mod:`.admission` — bounded per-class admission control with deadlines and
  load shedding (structured retry-after errors instead of unbounded queues);
- :mod:`.cache` — an LRU-by-bytes cache of materialized result Tables keyed
  on (plan fingerprint, catalog signature, config), invalidated by DDL/DML
  through the same versioning the plan cache uses;
- :mod:`.metrics` — counters + latency/queue-depth histograms aggregated
  from the per-node Tracer, surfaced as ``SHOW METRICS`` and ``/v1/metrics``.

:mod:`.runtime` ties them together into the worker pool the Presto server
runs queries on.
"""
from ..resilience.errors import ShutdownError
from .admission import (
    AdmissionController,
    DeadlineExceededError,
    QueryCancelledError,
    QueryTicket,
    QueueFullError,
)
from .cache import ResultCache, table_nbytes
from .metrics import Histogram, MetricsRegistry
from .runtime import ServingRuntime, current_ticket

__all__ = [
    "AdmissionController",
    "DeadlineExceededError",
    "Histogram",
    "MetricsRegistry",
    "QueryCancelledError",
    "QueryTicket",
    "QueueFullError",
    "ResultCache",
    "ServingRuntime",
    "ShutdownError",
    "current_ticket",
    "table_nbytes",
]
