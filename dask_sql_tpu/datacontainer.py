"""Data containers: the objects the Context registers and returns.

Role parity: reference datacontainer.py — ColumnContainer front/backend
mapping with zero-copy renames (datacontainer.py:53-171), DataContainer.assign
(datacontainer.py:217), SchemaContainer (datacontainer.py:281), Statistics
(datacontainer.py:174), FunctionDescription (datacontainer.py:9), UDF wrapper
(datacontainer.py:234-270).  Here the backend is a device `Table`; renames are
dictionary-key rewrites (no data movement, like the reference's mapping).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .columnar.table import Table
from .planner.catalog import FunctionDescription, Statistics  # re-export parity names

__all__ = [
    "ColumnContainer",
    "DataContainer",
    "SchemaContainer",
    "Statistics",
    "FunctionDescription",
]


class ColumnContainer:
    """Frontend->backend column mapping: renames/reorders without touching data."""

    def __init__(self, frontend_columns: List[str],
                 frontend_backend_mapping: Optional[Dict[str, str]] = None):
        self._frontend_columns = list(frontend_columns)
        self._mapping = dict(frontend_backend_mapping or {c: c for c in frontend_columns})

    @property
    def columns(self) -> List[str]:
        return list(self._frontend_columns)

    def get_backend_by_frontend_name(self, name: str) -> str:
        return self._mapping[name]

    def get_backend_by_frontend_index(self, index: int) -> str:
        return self._mapping[self._frontend_columns[index]]

    def limit_to(self, frontend_columns: List[str]) -> "ColumnContainer":
        return ColumnContainer(list(frontend_columns),
                               {c: self._mapping[c] for c in frontend_columns})

    def rename(self, columns: Dict[str, str]) -> "ColumnContainer":
        new_front = [columns.get(c, c) for c in self._frontend_columns]
        new_map = {}
        for old, new in zip(self._frontend_columns, new_front):
            new_map[new] = self._mapping[old]
        return ColumnContainer(new_front, new_map)

    def rename_handle_duplicates(self, from_columns: List[str],
                                 to_columns: List[str]) -> "ColumnContainer":
        new_map = {t: self._mapping[f] for f, t in zip(from_columns, to_columns)}
        return ColumnContainer(list(to_columns), new_map)

    def add(self, frontend_name: str, backend_name: Optional[str] = None) -> "ColumnContainer":
        backend_name = backend_name if backend_name is not None else frontend_name
        cc = ColumnContainer(self._frontend_columns, self._mapping)
        if frontend_name not in cc._frontend_columns:
            cc._frontend_columns.append(frontend_name)
        cc._mapping[frontend_name] = backend_name
        return cc

    def make_unique(self, prefix: str = "col") -> "ColumnContainer":
        new_names = [f"{prefix}_{i}" for i in range(len(self._frontend_columns))]
        return self.rename_handle_duplicates(self._frontend_columns, new_names)


import itertools as _itertools

_dc_serial = _itertools.count()


class DataContainer:
    """A device Table + its frontend column view."""

    def __init__(self, table: Table, column_container: Optional[ColumnContainer] = None):
        self.table = table
        self.column_container = column_container or ColumnContainer(table.column_names)
        #: unique serial for compile-cache keys (id() can be recycled)
        self.uid = next(_dc_serial)

    @property
    def df(self) -> Table:  # parity name: reference stores the dask df as .df
        return self.table

    def assign(self) -> Table:
        """Materialize the frontend view as a concrete Table (parity
        datacontainer.py:217)."""
        cols = {}
        for front in self.column_container.columns:
            back = self.column_container.get_backend_by_frontend_name(front)
            cols[front] = self.table.columns[back]
        return Table(cols, self.table.num_rows,
                     getattr(self.table, "row_valid", None))

    def to_pandas(self):
        return self.assign().to_pandas()


class LazyParquetContainer(DataContainer):
    """Location-backed table that stays on disk until scanned.

    Parity: the reference's non-persisted location tables (input_utils
    convert.py:70: `persist=False` keeps the dask read graph lazy, letting
    `filters=` pushdown reach pyarrow).  `scan()` reads only the projected
    columns with row-group filters — the IO half of predicate pushdown.
    """

    def __init__(self, location: str, fields, statistics=None, file_format: str = "parquet"):
        self.location = location
        self.file_format = file_format
        self.fields = list(fields)
        self.statistics = statistics
        self._table: Optional[Table] = None
        self.column_container = ColumnContainer([f.name for f in self.fields])
        self.uid = next(_dc_serial)

    @property
    def table(self) -> Table:
        if self._table is None:
            self._table = self.scan()
        return self._table

    @table.setter
    def table(self, value):  # pragma: no cover - compat shim
        self._table = value

    def scan(self, columns=None, filters=None) -> Table:
        import pyarrow as pa
        import pyarrow.parquet as pq

        from .physical.utils.statistics import _paths_for

        paths = _paths_for(self.location)
        tables = [pq.read_table(p, columns=list(columns) if columns else None,
                                filters=filters) for p in paths]
        at = pa.concat_tables(tables) if len(tables) > 1 else tables[0]
        return Table.from_arrow(at)

    def assign(self) -> Table:
        return self.table


@dataclass
class SchemaContainer:
    """Parity: reference SchemaContainer (datacontainer.py:281)."""

    name: str
    tables: Dict[str, DataContainer] = field(default_factory=dict)
    statistics: Dict[str, Statistics] = field(default_factory=dict)
    functions: Dict[str, FunctionDescription] = field(default_factory=dict)
    function_lists: Dict[str, List[FunctionDescription]] = field(default_factory=dict)
    models: Dict[str, Tuple[object, List[str]]] = field(default_factory=dict)
    experiments: Dict[str, object] = field(default_factory=dict)
    filepaths: Dict[str, str] = field(default_factory=dict)
