"""Semantic result reuse (materialize/): sub-plan stem materialization,
subsumption answering over ParamRef intervals, incremental maintenance of
aggregate states across appends, and the epoch-scoped invalidation that
keeps all three tiers sound."""
import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context

pytestmark = pytest.mark.reuse


def _ctx(df=None, name="t", **config):
    ctx = Context()
    if config:
        ctx.config.update(config)
    if df is not None:
        ctx.create_table(name, df)
    return ctx


def _df(n=200, seed=0):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "a": rng.integers(0, 100, n).astype(np.int64),
        "b": rng.random(n) * 100.0,
        "k": rng.integers(0, 5, n).astype(np.int64),
    })


# ----------------------------------------------------------- subsumption
def test_subsumption_serves_tighter_literal():
    ctx = _ctx(_df())
    wide = ctx.sql("SELECT a, k FROM t WHERE a < 80").compute()
    assert len(wide)
    tight = ctx.sql("SELECT a, k FROM t WHERE a < 30").compute()
    assert ctx.metrics.counter("serving.reuse.subsumption.hits") == 1
    cold = _ctx(_df()).sql("SELECT a, k FROM t WHERE a < 30").compute()
    pd.testing.assert_frame_equal(tight.reset_index(drop=True),
                                  cold.reset_index(drop=True))


def test_subsumption_property_sweep():
    """Random ParamRef intervals x comparators: every answer byte-identical
    to a cold execution, whether subsumption served it or not."""
    rng = np.random.default_rng(7)
    df = _df(300, seed=3)
    ops = ["<", "<=", ">", ">=", "="]
    served = 0
    for trial in range(30):
        op = ops[trial % len(ops)]
        v1, v2 = sorted(rng.integers(0, 100, 2).tolist())
        # cached literal loose, probe tight (for =, identical values probe
        # the exact-match path through the same machinery)
        if op in ("<", "<="):
            cached_v, probe_v = v2, v1
        elif op in (">", ">="):
            cached_v, probe_v = v1, v2
        else:
            cached_v = probe_v = v1
        ctx = _ctx(df)
        ctx.sql(f"SELECT a, k FROM t WHERE a {op} {cached_v}").compute()
        got = ctx.sql(f"SELECT a, k FROM t WHERE a {op} {probe_v}").compute()
        served += ctx.metrics.counter("serving.reuse.subsumption.hits")
        cold = _ctx(df).sql(
            f"SELECT a, k FROM t WHERE a {op} {probe_v}").compute()
        pd.testing.assert_frame_equal(got.reset_index(drop=True),
                                      cold.reset_index(drop=True))
    # the sweep must actually exercise the tier, not just fall through
    assert served >= 10


def test_subsumption_declines_nullable_column():
    """NULL-able columns (here: float, always nullable by catalog
    convention) get exact-match slots only — a tighter float literal is
    never served by re-filtering, but the answer stays correct."""
    ctx = _ctx(_df())
    ctx.sql("SELECT a FROM t WHERE b < 80.0").compute()
    got = ctx.sql("SELECT a FROM t WHERE b < 30.0").compute()
    assert ctx.metrics.counter("serving.reuse.subsumption.hits") == 0
    cold = _ctx(_df()).sql("SELECT a FROM t WHERE b < 30.0").compute()
    pd.testing.assert_frame_equal(got.reset_index(drop=True),
                                  cold.reset_index(drop=True))


def test_interval_algebra_float_boundary_declines():
    """The interval algebra is provable-only: equality at float endpoints
    declines (rounding could flip boundary membership), integer endpoints
    prove."""
    from dask_sql_tpu.analysis.estimator import (
        interval_contains,
        param_slot_contains,
        pred_interval,
    )

    assert param_slot_contains("lt", 100, 50) is True
    assert param_slot_contains("lt", 50, 100) is False
    assert param_slot_contains("le", 50, 50) is True
    assert param_slot_contains("le", 50.0, 50.0, float_domain=True) is False
    assert param_slot_contains("lt", 100.0, 50.0, float_domain=True) is True
    assert param_slot_contains("eq", 5, 5) is True
    assert param_slot_contains("eq", 5.0, 5.0, float_domain=True) is False
    outer = pred_interval("lt", 100)
    inner = pred_interval("le", 99)
    assert interval_contains(outer, inner, float_domain=False) is True
    # open outer endpoint cannot prove a closed inner one at the same value
    assert interval_contains(pred_interval("lt", 99),
                             pred_interval("le", 99)) is False


# ------------------------------------------------- stem materialization
def test_stem_materialization_and_rewrite():
    df = _df(4000, seed=1)
    ctx = _ctx(df, **{"serving.materialize.min_bytes": 1})
    # two sibling projections over one scan->filter stem pin it ...
    ctx.sql("SELECT a FROM t WHERE a > 3 AND b < 90.0").compute()
    ctx.sql("SELECT b FROM t WHERE a > 3 AND b < 90.0").compute()
    assert ctx.metrics.counter("serving.materialize.stored") == 1
    # ... and a third sibling scans the pinned stem instead of the table
    got = ctx.sql("SELECT k, a FROM t WHERE a > 3 AND b < 90.0").compute()
    assert ctx.metrics.counter("serving.materialize.hits") >= 1
    cold = _ctx(df).sql("SELECT k, a FROM t WHERE a > 3 AND b < 90.0").compute()
    pd.testing.assert_frame_equal(got.reset_index(drop=True),
                                  cold.reset_index(drop=True))


def test_stem_flight_events_and_ledger_reconcile():
    from dask_sql_tpu.observability import flight

    df = _df(4000, seed=2)
    ctx = _ctx(df, **{"serving.materialize.min_bytes": 1})
    flight.RECORDER.clear()
    ctx.sql("SELECT a FROM t WHERE k = 2").compute()
    ctx.sql("SELECT b FROM t WHERE k = 2").compute()
    assert flight.RECORDER.events(name="materialize.store")
    pinned = ctx.materialize.pinned_bytes()
    assert pinned > 0
    assert ctx.ledger.snapshot()["materializedBytes"] == pinned
    ctx.sql("SELECT k FROM t WHERE k = 2").compute()
    assert flight.RECORDER.events(name="materialize.hit")
    # eviction returns the ledger component to idle
    ctx.materialize.invalidate_all()
    assert ctx.materialize.pinned_bytes() == 0
    assert ctx.ledger.snapshot()["materializedBytes"] == 0
    assert flight.RECORDER.events(name="materialize.evict")


# -------------------------------------------- invalidation hardening
def test_append_invalidates_exactly_dependents():
    """Appending to one table drops cached results and materializations
    over THAT table (epoch-scoped), while results over other tables
    survive and stay hittable."""
    ctx = Context()
    ctx.create_table("t1", _df(100, seed=4))
    ctx.create_table("t2", _df(100, seed=5))
    r1 = ctx.sql("SELECT SUM(a) AS s FROM t1").compute()
    r2 = ctx.sql("SELECT SUM(a) AS s FROM t2").compute()
    base_hits = ctx._result_cache.stats.hits
    ctx.append_rows("t1", pd.DataFrame({
        "a": [1000], "b": [1.0], "k": [0]}))
    # t2's entry survived and still serves
    again2 = ctx.sql("SELECT SUM(a) AS s FROM t2").compute()
    assert ctx._result_cache.stats.hits == base_hits + 1
    pd.testing.assert_frame_equal(again2, r2)
    # t1's entry is epoch-invalidated: recomputes, including the delta
    again1 = ctx.sql("SELECT SUM(a) AS s FROM t1").compute()
    assert again1["s"][0] == r1["s"][0] + 1000


def test_replace_invalidates_exactly_dependents():
    ctx = Context()
    ctx.create_table("t1", _df(100, seed=6))
    ctx.create_table("t2", _df(100, seed=7))
    ctx.sql("SELECT COUNT(*) AS c FROM t1").compute()
    r2 = ctx.sql("SELECT SUM(k) AS s FROM t2").compute()
    base_hits = ctx._result_cache.stats.hits
    ctx.create_table("t1", _df(50, seed=8))  # replace
    again2 = ctx.sql("SELECT SUM(k) AS s FROM t2").compute()
    assert ctx._result_cache.stats.hits == base_hits + 1
    pd.testing.assert_frame_equal(again2, r2)
    assert ctx.sql("SELECT COUNT(*) AS c FROM t1").compute()["c"][0] == 50


def test_append_refreshes_pinned_stem_without_rescan():
    df = _df(4000, seed=9)
    ctx = _ctx(df, **{"serving.materialize.min_bytes": 1})
    ctx.sql("SELECT a FROM t WHERE a > 10").compute()
    ctx.sql("SELECT b FROM t WHERE a > 10").compute()
    assert ctx.metrics.counter("serving.materialize.stored") == 1
    rows_before = ctx.materialize.rows()[0][3]
    ctx.append_rows("t", pd.DataFrame({
        "a": [50, 5], "b": [1.0, 2.0], "k": [0, 0]}))
    assert ctx.metrics.counter("serving.materialize.refreshed") == 1
    # only the qualifying delta row folded in — history was not rescanned
    assert ctx.materialize.rows()[0][3] == rows_before + 1
    got = ctx.sql("SELECT k FROM t WHERE a > 10").compute()
    assert ctx.metrics.counter("serving.materialize.hits") >= 1
    expected = pd.concat(
        [df, pd.DataFrame({"a": [50, 5], "b": [1.0, 2.0], "k": [0, 0]})],
        ignore_index=True)
    assert len(got) == int((expected["a"] > 10).sum())


# ------------------------------------------- incremental maintenance
def test_incremental_fold_matches_pandas():
    df = _df(500, seed=10)
    ctx = _ctx(df)
    q = "SELECT k, SUM(a) AS s, COUNT(*) AS c FROM t GROUP BY k"
    ctx.sql(q).compute()
    delta = _df(40, seed=11)
    ctx.append_rows("t", delta)
    assert ctx.metrics.counter("serving.reuse.incremental.folds") >= 1
    got = ctx.sql(q).compute()
    assert ctx.metrics.counter("serving.reuse.incremental.hits") == 1
    full = pd.concat([df, delta], ignore_index=True)
    expected = (full.groupby("k", as_index=False)
                .agg(s=("a", "sum"), c=("a", "count")))
    got = got.sort_values("k").reset_index(drop=True)
    expected = expected.sort_values("k").reset_index(drop=True)
    assert got["k"].tolist() == expected["k"].tolist()
    assert got["s"].tolist() == expected["s"].tolist()
    assert got["c"].tolist() == expected["c"].tolist()


def test_incremental_state_survives_repeated_appends():
    df = _df(300, seed=12)
    ctx = _ctx(df)
    q = "SELECT SUM(a) AS s FROM t"
    ctx.sql(q).compute()
    frames = [df]
    for seed in (13, 14, 15):
        delta = _df(20, seed=seed)
        ctx.append_rows("t", delta)
        frames.append(delta)
        got = ctx.sql(q).compute()
        assert got["s"][0] == pd.concat(frames)["a"].sum()
    assert ctx.metrics.counter("serving.reuse.incremental.hits") == 3


# ------------------------------------------------------- append surface
def test_append_rows_api():
    df = _df(50, seed=16)
    ctx = _ctx(df)
    n = ctx.append_rows("t", pd.DataFrame({
        "a": [1, 2], "b": [0.5, 0.25], "k": [1, 1]}))
    assert n == 2
    assert ctx.sql("SELECT COUNT(*) AS c FROM t").compute()["c"][0] == 52
    with pytest.raises(KeyError):
        ctx.append_rows("missing", df)


def test_insert_into_sql():
    ctx = _ctx(_df(50, seed=17))
    out = ctx.sql("INSERT INTO t VALUES (7, 0.5, 1), (8, 0.25, 2)").compute()
    assert out["Inserted"][0] == "2"
    out = ctx.sql("INSERT INTO t SELECT a, b, k FROM t WHERE k = 2").compute()
    assert int(out["Inserted"][0]) >= 1
    assert ctx.metrics.counter("serving.reuse.append_rows") >= 3
    with pytest.raises(RuntimeError, match="expects 3 columns"):
        ctx.sql("INSERT INTO t VALUES (1)").compute()
    with pytest.raises(RuntimeError, match="not present"):
        ctx.sql("INSERT INTO missing VALUES (1, 2.0, 3)").compute()


def test_show_materialized_sql():
    df = _df(4000, seed=18)
    ctx = _ctx(df, **{"serving.materialize.min_bytes": 1})
    out = ctx.sql("SHOW MATERIALIZED").compute()
    assert list(out.columns) == ["Kind", "Fingerprint", "Table", "Rows",
                                 "Bytes", "Hits", "Epoch"]
    assert len(out) == 0
    ctx.sql("SELECT a FROM t WHERE b < 50.0").compute()
    ctx.sql("SELECT k FROM t WHERE b < 50.0").compute()
    ctx.sql("SELECT k, SUM(a) AS s FROM t GROUP BY k").compute()
    ctx.append_rows("t", _df(10, seed=19))
    out = ctx.sql("SHOW MATERIALIZED").compute()
    kinds = set(out["Kind"])
    assert "stem" in kinds and "incremental" in kinds
    like = ctx.sql("SHOW MATERIALIZED LIKE 'stem'").compute()
    assert set(like["Kind"]) == {"stem"}


def test_parser_parity_new_statements():
    """Native (C++) and Python parsers produce identical ASTs for the
    reuse-surface statements."""
    from dask_sql_tpu.planner.native_bridge import native_parse
    from dask_sql_tpu.planner.parser import Parser

    for sql in ["INSERT INTO s.t VALUES (1, 2.5, 'x')",
                "INSERT INTO t SELECT a, b FROM u WHERE a < 3",
                "SHOW MATERIALIZED",
                "SHOW MATERIALIZED LIKE 'stem%'"]:
        py = Parser(sql).parse_statements()
        nat = native_parse(sql)
        if nat is None:  # native lib unavailable: Python path already covers
            continue
        assert repr(nat) == repr(py), sql
