"""TPC-DS schema + small synthetic data generator for the q1-q99 suite.

Parity: the reference's q1-q99 yardstick reads pre-generated parquet from
--data_dir (reference tests/unit/test_queries.py); here the tables are
generated in-process (like tests/tpch.py) with domains matched to the
qualification-query predicates so queries exercise real paths and return
non-degenerate results at tiny scale.
"""
from __future__ import annotations

import numpy as np
import pandas as pd

_STATES = ["TN", "GA", "CA", "WA", "TX", "OH", "OR", "NM", "KY", "VA", "MS",
           "IN", "ND", "OK", "IL", "NJ", "WI", "CT", "LA", "IA", "AR", "CO",
           "MN", "MO"]
_COUNTIES = ["Williamson County", "Rush County", "Toole County",
             "Jefferson County", "Dona Ana County", "La Porte County",
             "Franklin Parish", "Bronx County", "Orange County",
             "Ziebach County", "Walker County"]
_CITIES = ["Fairview", "Midway", "Edgewood", "Oak Grove", "Five Points",
           "Centerville", "Liberty", "Union", "Salem", "Glenwood"]
_CATEGORIES = ["Books", "Children", "Electronics", "Women", "Music", "Men",
               "Sports", "Home", "Jewelry", "Shoes"]
_CLASSES = ["personal", "portable", "reference", "self-help", "accessories",
            "classical", "fragrances", "pants", "computers", "stereo",
            "football", "shirts", "birdal", "dresses", "maternity"]
_BRANDS = ["scholaramalgamalg #14", "scholaramalgamalg #7",
           "exportiunivamalg #9", "scholaramalgamalg #9", "amalgimporto #1",
           "edu packscholar #1", "exportiimporto #1", "importoamalg #1",
           "corpnameless #3", "univbrand #6"]
_COLORS = ["pale", "powder", "khaki", "brown", "honeydew", "floral", "deep",
           "light", "cornflower", "midnight", "snow", "cyan", "papaya",
           "orange", "frosted", "forest", "ghost", "slate", "blanched",
           "burnished", "purple", "burlywood", "indian", "spring", "medium"]
_UNITS = ["Ounce", "Oz", "Bunch", "Ton", "N/A", "Dozen", "Box", "Pound",
          "Pallet", "Gross", "Cup", "Dram", "Each", "Tbl", "Lb", "Bundle"]
_SIZES = ["medium", "extra large", "N/A", "small", "petite", "large"]
_DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
              "Friday", "Saturday"]
_EDUCATION = ["Unknown", "College", "Advanced Degree", "2 yr Degree",
              "4 yr Degree", "Primary", "Secondary"]
_MARITAL = ["M", "S", "D", "W", "U"]
_BUY_POTENTIAL = [">10000", "Unknown", "1001-5000", "0-500", "501-1000",
                  "5001-10000"]
_MEALS = ["breakfast", "dinner", "lunch", ""]
_COUNTRIES = ["United States"]


def _dates() -> pd.DataFrame:
    days = pd.date_range("1998-01-01", "2002-12-31", freq="D")
    n = len(days)
    sk = np.arange(1, n + 1, dtype=np.int64)
    year = days.year.to_numpy()
    moy = days.month.to_numpy()
    return pd.DataFrame({
        "d_date_sk": sk,
        "d_date_id": [f"AAAAAAAA{int(s):08d}" for s in sk],
        "d_date": days.to_numpy().astype("datetime64[ns]"),
        "d_month_seq": ((year - 1900) * 12 + (moy - 1)).astype(np.int64),
        "d_week_seq": ((days - pd.Timestamp("1900-01-01")).days.to_numpy() // 7
                       ).astype(np.int64),
        "d_quarter_seq": ((year - 1900) * 4 + (moy - 1) // 3).astype(np.int64),
        "d_year": year.astype(np.int64),
        "d_dow": days.dayofweek.to_numpy().astype(np.int64),  # Mon=0
        "d_moy": moy.astype(np.int64),
        "d_dom": days.day.to_numpy().astype(np.int64),
        "d_qoy": days.quarter.to_numpy().astype(np.int64),
        "d_fy_year": year.astype(np.int64),
        "d_day_name": [_DAY_NAMES[(d + 1) % 7] for d in days.dayofweek],
        "d_quarter_name": [f"{y}Q{q}" for y, q in
                           zip(year, days.quarter.to_numpy())],
        "d_holiday": np.where(days.day.to_numpy() % 13 == 0, "Y", "N"),
        "d_weekend": np.where(days.dayofweek.to_numpy() >= 5, "Y", "N"),
        "d_following_holiday": np.where(days.day.to_numpy() % 13 == 1, "Y", "N"),
        "d_first_dom": sk - days.day.to_numpy() + 1,
        "d_last_dom": sk - days.day.to_numpy() + days.days_in_month.to_numpy(),
        "d_current_day": "N",
        "d_current_week": "N",
        "d_current_month": "N",
        "d_current_quarter": "N",
        "d_current_year": "N",
    })


def _times(rng) -> pd.DataFrame:
    # one row per 30s of the day keeps it small but covers hour/minute filters
    secs = np.arange(0, 86400, 30, dtype=np.int64)
    return pd.DataFrame({
        "t_time_sk": secs,
        "t_time_id": [f"T{int(s):08d}" for s in secs],
        "t_time": secs,
        "t_hour": secs // 3600,
        "t_minute": (secs % 3600) // 60,
        "t_second": secs % 60,
        "t_am_pm": np.where(secs < 43200, "AM", "PM"),
        "t_shift": np.where(secs < 28800, "first",
                            np.where(secs < 57600, "second", "third")),
        "t_sub_shift": "morning",
        "t_meal_time": [_MEALS[int(h) // 7 % 4] for h in secs // 3600],
    })


def _pick(rng, values, n):
    return np.array(values, dtype=object)[rng.randint(0, len(values), n)]


def _null_some(rng, arr: np.ndarray, frac: float) -> np.ndarray:
    out = arr.astype(float)
    out[rng.rand(len(out)) < frac] = np.nan
    return out


def generate(scale_rows: int = 2000, seed: int = 42):
    """All 24 TPC-DS tables; `scale_rows` sizes store_sales, others scale off it."""
    rng = np.random.RandomState(seed)
    date_dim = _dates()
    nd = len(date_dim)
    time_dim = _times(rng)

    n_item = max(scale_rows // 20, 50)
    n_cust = max(scale_rows // 10, 100)
    n_addr = max(n_cust // 2, 50)
    n_cd = 200
    n_hd = 72
    n_store = 12
    n_wh = 5
    n_promo = 30
    n_cc = 6
    n_cp = 20
    n_web = 6
    n_wp = 20
    n_ib = 20

    item = pd.DataFrame({
        "i_item_sk": np.arange(1, n_item + 1, dtype=np.int64),
        "i_item_id": [f"AAAAAAAA{k % (n_item // 2 + 1):08d}"
                      for k in range(1, n_item + 1)],
        "i_rec_start_date": pd.Timestamp("1997-10-27"),
        "i_rec_end_date": pd.NaT,
        "i_item_desc": [f"item description {k} longer text for substr"
                        for k in range(1, n_item + 1)],
        "i_current_price": np.round(rng.uniform(0.5, 100, n_item), 2),
        "i_wholesale_cost": np.round(rng.uniform(0.3, 80, n_item), 2),
        "i_brand_id": rng.randint(1, 10, n_item).astype(np.int64) * 1000 + 1,
        "i_brand": _pick(rng, _BRANDS, n_item),
        "i_class_id": rng.randint(1, 16, n_item).astype(np.int64),
        "i_class": _pick(rng, _CLASSES, n_item),
        "i_category_id": rng.randint(1, 11, n_item).astype(np.int64),
        "i_category": _pick(rng, _CATEGORIES, n_item),
        "i_manufact_id": rng.randint(1, 1000, n_item).astype(np.int64),
        "i_manufact": [f"manufact{k % 100}" for k in range(n_item)],
        "i_size": _pick(rng, _SIZES, n_item),
        "i_formulation": [f"form{k % 17}" for k in range(n_item)],
        "i_color": _pick(rng, _COLORS, n_item),
        "i_units": _pick(rng, _UNITS, n_item),
        "i_container": "Unknown",
        "i_manager_id": rng.randint(1, 100, n_item).astype(np.int64),
        "i_product_name": [f"product {k}" for k in range(1, n_item + 1)],
    })
    customer_address = pd.DataFrame({
        "ca_address_sk": np.arange(1, n_addr + 1, dtype=np.int64),
        "ca_address_id": [f"AAAAAAAA{k:08d}" for k in range(1, n_addr + 1)],
        "ca_street_number": [str(100 + k) for k in range(n_addr)],
        "ca_street_name": [f"Main St {k % 40}" for k in range(n_addr)],
        "ca_street_type": "Street",
        "ca_suite_number": [f"Suite {k % 20}" for k in range(n_addr)],
        "ca_city": _pick(rng, _CITIES, n_addr),
        "ca_county": _pick(rng, _COUNTIES, n_addr),
        "ca_state": _pick(rng, _STATES, n_addr),
        "ca_zip": [f"{z:05d}" for z in
                   rng.choice([24128, 76232, 65084, 85669, 86197, 88274, 83405,
                               86475, 85392, 85460, 80348, 81792, 30903, 48583],
                              n_addr)],
        "ca_country": _pick(rng, _COUNTRIES, n_addr),
        "ca_gmt_offset": rng.choice([-5.0, -6.0, -7.0, -8.0], n_addr),
        "ca_location_type": "single family",
    })
    customer_demographics = pd.DataFrame({
        "cd_demo_sk": np.arange(1, n_cd + 1, dtype=np.int64),
        "cd_gender": _pick(rng, ["M", "F"], n_cd),
        "cd_marital_status": _pick(rng, _MARITAL, n_cd),
        "cd_education_status": _pick(rng, _EDUCATION, n_cd),
        "cd_purchase_estimate": rng.randint(1, 10, n_cd).astype(np.int64) * 500,
        "cd_credit_rating": _pick(rng, ["Good", "Low Risk", "High Risk",
                                        "Unknown"], n_cd),
        "cd_dep_count": rng.randint(0, 7, n_cd).astype(np.int64),
        "cd_dep_employed_count": rng.randint(0, 7, n_cd).astype(np.int64),
        "cd_dep_college_count": rng.randint(0, 7, n_cd).astype(np.int64),
    })
    household_demographics = pd.DataFrame({
        "hd_demo_sk": np.arange(1, n_hd + 1, dtype=np.int64),
        "hd_income_band_sk": rng.randint(1, n_ib + 1, n_hd).astype(np.int64),
        "hd_buy_potential": _pick(rng, _BUY_POTENTIAL, n_hd),
        "hd_dep_count": rng.randint(0, 10, n_hd).astype(np.int64),
        "hd_vehicle_count": rng.randint(0, 7, n_hd).astype(np.int64),
    })
    income_band = pd.DataFrame({
        "ib_income_band_sk": np.arange(1, n_ib + 1, dtype=np.int64),
        "ib_lower_bound": np.arange(0, n_ib, dtype=np.int64) * 10000,
        "ib_upper_bound": (np.arange(0, n_ib, dtype=np.int64) + 1) * 10000,
    })
    customer = pd.DataFrame({
        "c_customer_sk": np.arange(1, n_cust + 1, dtype=np.int64),
        "c_customer_id": [f"AAAAAAAA{k:08d}" for k in range(1, n_cust + 1)],
        "c_current_cdemo_sk": rng.randint(1, n_cd + 1, n_cust).astype(np.int64),
        "c_current_hdemo_sk": rng.randint(1, n_hd + 1, n_cust).astype(np.int64),
        "c_current_addr_sk": rng.randint(1, n_addr + 1, n_cust).astype(np.int64),
        "c_first_shipto_date_sk": rng.randint(1, nd + 1, n_cust).astype(np.int64),
        "c_first_sales_date_sk": rng.randint(1, nd + 1, n_cust).astype(np.int64),
        "c_salutation": _pick(rng, ["Mr.", "Ms.", "Dr.", "Mrs.", "Sir"], n_cust),
        "c_first_name": _pick(rng, ["James", "Mary", "John", "Linda", "Ann",
                                    "Luis", "Wei", "Aisha"], n_cust),
        "c_last_name": _pick(rng, ["Smith", "Jones", "Garcia", "Chen", "Khan",
                                   "Brown", "Lee", "Patel"], n_cust),
        "c_preferred_cust_flag": _pick(rng, ["Y", "N"], n_cust),
        "c_birth_day": rng.randint(1, 29, n_cust).astype(np.int64),
        "c_birth_month": rng.randint(1, 13, n_cust).astype(np.int64),
        "c_birth_year": rng.randint(1930, 1995, n_cust).astype(np.int64),
        "c_birth_country": _pick(rng, ["UNITED STATES", "CANADA", "MEXICO",
                                       "FRANCE"], n_cust),
        "c_login": "",
        "c_email_address": [f"user{k}@example.com" for k in range(n_cust)],
        "c_last_review_date_sk": rng.randint(1, nd + 1, n_cust).astype(np.int64),
    })
    store = pd.DataFrame({
        "s_store_sk": np.arange(1, n_store + 1, dtype=np.int64),
        "s_store_id": [f"AAAAAAAA{k % (n_store // 2):08d}"
                       for k in range(n_store)],
        "s_rec_start_date": pd.Timestamp("1997-03-13"),
        "s_rec_end_date": pd.NaT,
        "s_closed_date_sk": _null_some(
            rng, rng.randint(1, nd + 1, n_store), 0.7),
        "s_store_name": _pick(rng, ["ese", "ought", "able", "pri", "bar"],
                              n_store),
        "s_number_employees": rng.randint(200, 300, n_store).astype(np.int64),
        "s_floor_space": rng.randint(5000000, 9999999, n_store).astype(np.int64),
        "s_hours": "8AM-8PM",
        "s_manager": "William Ward",
        "s_market_id": rng.randint(1, 11, n_store).astype(np.int64),
        "s_geography_class": "Unknown",
        "s_market_desc": "market description text",
        "s_market_manager": "Scott Smith",
        "s_division_id": 1,
        "s_division_name": "Unknown",
        "s_company_id": 1,
        "s_company_name": "Unknown",
        "s_street_number": [str(100 + k) for k in range(n_store)],
        "s_street_name": "Main",
        "s_street_type": "Street",
        "s_suite_number": "Suite 100",
        "s_city": _pick(rng, _CITIES[:4], n_store),
        "s_county": _pick(rng, _COUNTIES[:2], n_store),
        "s_state": _pick(rng, ["TN", "GA"], n_store),
        "s_zip": [f"{z:05d}" for z in
                  rng.choice([24128, 76232, 85669, 30903], n_store)],
        "s_country": "United States",
        "s_gmt_offset": -5.0,
        "s_tax_precentage": 0.03,
    })
    warehouse = pd.DataFrame({
        "w_warehouse_sk": np.arange(1, n_wh + 1, dtype=np.int64),
        "w_warehouse_id": [f"AAAAAAAA{k:08d}" for k in range(1, n_wh + 1)],
        "w_warehouse_name": [f"Warehouse number {k} with a long name"
                             for k in range(1, n_wh + 1)],
        "w_warehouse_sq_ft": rng.randint(50000, 999999, n_wh).astype(np.int64),
        "w_street_number": "100",
        "w_street_name": "Main",
        "w_street_type": "Street",
        "w_suite_number": "Suite 1",
        "w_city": _pick(rng, _CITIES, n_wh),
        "w_county": _pick(rng, _COUNTIES, n_wh),
        "w_state": _pick(rng, _STATES, n_wh),
        "w_zip": "30903",
        "w_country": "United States",
        "w_gmt_offset": -5.0,
    })
    promotion = pd.DataFrame({
        "p_promo_sk": np.arange(1, n_promo + 1, dtype=np.int64),
        "p_promo_id": [f"AAAAAAAA{k:08d}" for k in range(1, n_promo + 1)],
        "p_start_date_sk": rng.randint(1, nd + 1, n_promo).astype(np.int64),
        "p_end_date_sk": rng.randint(1, nd + 1, n_promo).astype(np.int64),
        "p_item_sk": rng.randint(1, n_item + 1, n_promo).astype(np.int64),
        "p_cost": 1000.0,
        "p_response_target": 1,
        "p_promo_name": _pick(rng, ["ought", "able", "pri"], n_promo),
        "p_channel_dmail": _pick(rng, ["Y", "N"], n_promo),
        "p_channel_email": _pick(rng, ["Y", "N"], n_promo),
        "p_channel_catalog": _pick(rng, ["Y", "N"], n_promo),
        "p_channel_tv": _pick(rng, ["Y", "N"], n_promo),
        "p_channel_radio": _pick(rng, ["Y", "N"], n_promo),
        "p_channel_press": _pick(rng, ["Y", "N"], n_promo),
        "p_channel_event": _pick(rng, ["Y", "N"], n_promo),
        "p_channel_demo": _pick(rng, ["Y", "N"], n_promo),
        "p_channel_details": "details",
        "p_purpose": "Unknown",
        "p_discount_active": "N",
    })
    call_center = pd.DataFrame({
        "cc_call_center_sk": np.arange(1, n_cc + 1, dtype=np.int64),
        "cc_call_center_id": [f"AAAAAAAA{k:08d}" for k in range(1, n_cc + 1)],
        "cc_name": [f"call center {k}" for k in range(1, n_cc + 1)],
        "cc_class": "medium",
        "cc_employees": rng.randint(100, 700, n_cc).astype(np.int64),
        "cc_manager": "Bob Belcher",
        "cc_county": _pick(rng, _COUNTIES[:1], n_cc),
        "cc_state": _pick(rng, ["TN", "GA"], n_cc),
    })
    catalog_page = pd.DataFrame({
        "cp_catalog_page_sk": np.arange(1, n_cp + 1, dtype=np.int64),
        "cp_catalog_page_id": [f"AAAAAAAA{k:08d}" for k in range(1, n_cp + 1)],
        "cp_catalog_number": rng.randint(1, 10, n_cp).astype(np.int64),
        "cp_catalog_page_number": np.arange(1, n_cp + 1, dtype=np.int64),
        "cp_department": "DEPARTMENT",
        "cp_description": "catalog page description",
        "cp_type": "monthly",
    })
    web_site = pd.DataFrame({
        "web_site_sk": np.arange(1, n_web + 1, dtype=np.int64),
        "web_site_id": [f"AAAAAAAA{k:08d}" for k in range(1, n_web + 1)],
        "web_name": [f"site_{k}" for k in range(n_web)],
        "web_manager": "Adam Stonge",
        "web_company_id": rng.randint(1, 7, n_web).astype(np.int64),
        "web_company_name": _pick(rng, ["pri", "able", "ought", "ese"], n_web),
    })
    web_page = pd.DataFrame({
        "wp_web_page_sk": np.arange(1, n_wp + 1, dtype=np.int64),
        "wp_web_page_id": [f"AAAAAAAA{k:08d}" for k in range(1, n_wp + 1)],
        "wp_creation_date_sk": rng.randint(1, nd + 1, n_wp).astype(np.int64),
        "wp_access_date_sk": rng.randint(1, nd + 1, n_wp).astype(np.int64),
        "wp_autogen_flag": _pick(rng, ["Y", "N"], n_wp),
        "wp_url": "http://www.foo.com",
        "wp_type": _pick(rng, ["general", "welcome", "protected"], n_wp),
        "wp_char_count": rng.randint(4000, 6000, n_wp).astype(np.int64),
        "wp_link_count": rng.randint(2, 25, n_wp).astype(np.int64),
        "wp_image_count": rng.randint(1, 7, n_wp).astype(np.int64),
    })
    reason = pd.DataFrame({
        "r_reason_sk": np.arange(1, 36, dtype=np.int64),
        "r_reason_id": [f"AAAAAAAA{k:08d}" for k in range(1, 36)],
        "r_reason_desc": [f"reason {k}" for k in range(1, 36)],
    })
    ship_mode = pd.DataFrame({
        "sm_ship_mode_sk": np.arange(1, 21, dtype=np.int64),
        "sm_ship_mode_id": [f"AAAAAAAA{k:08d}" for k in range(1, 21)],
        "sm_type": np.array(["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR",
                             "LIBRARY"] * 4, dtype=object),
        "sm_code": np.array(["AIR", "SURFACE", "SEA", "AIR", "SURFACE"] * 4,
                            dtype=object),
        "sm_carrier": np.array(["DHL", "BARIAN", "UPS", "FEDEX", "USPS"] * 4,
                               dtype=object),
        "sm_contract": "contract",
    })

    def sales_common(n):
        return {
            "sold_date_sk": rng.randint(1, nd + 1, n).astype(np.int64),
            "sold_time_sk": time_dim["t_time_sk"].to_numpy()[
                rng.randint(0, len(time_dim), n)],
            "item_sk": rng.randint(1, n_item + 1, n).astype(np.int64),
            "quantity": rng.randint(1, 101, n).astype(np.int64),
            "wholesale_cost": np.round(rng.uniform(1, 100, n), 2),
            "list_price": np.round(rng.uniform(1, 200, n), 2),
            "sales_price": np.round(rng.uniform(1, 200, n), 2),
            "ext_discount_amt": np.round(rng.uniform(0, 100, n), 2),
            "ext_sales_price": np.round(rng.uniform(1, 2000, n), 2),
            "ext_wholesale_cost": np.round(rng.uniform(1, 2000, n), 2),
            "ext_list_price": np.round(rng.uniform(1, 4000, n), 2),
            "ext_tax": np.round(rng.uniform(0, 200, n), 2),
            "coupon_amt": np.round(rng.uniform(0, 500, n), 2),
            "net_paid": np.round(rng.uniform(1, 2000, n), 2),
            "net_paid_inc_tax": np.round(rng.uniform(1, 2200, n), 2),
            "net_profit": np.round(rng.uniform(-500, 2000, n), 2),
        }

    n_ss = scale_rows
    sc = sales_common(n_ss)
    store_sales = pd.DataFrame({
        "ss_sold_date_sk": sc["sold_date_sk"],
        "ss_sold_time_sk": sc["sold_time_sk"],
        "ss_item_sk": sc["item_sk"],
        "ss_customer_sk": rng.randint(1, n_cust + 1, n_ss).astype(np.int64),
        "ss_cdemo_sk": rng.randint(1, n_cd + 1, n_ss).astype(np.int64),
        "ss_hdemo_sk": rng.randint(1, n_hd + 1, n_ss).astype(np.int64),
        "ss_addr_sk": _null_some(rng, rng.randint(1, n_addr + 1, n_ss), 0.02),
        "ss_store_sk": _null_some(rng, rng.randint(1, n_store + 1, n_ss), 0.02),
        "ss_promo_sk": rng.randint(1, n_promo + 1, n_ss).astype(np.int64),
        "ss_ticket_number": (np.arange(n_ss, dtype=np.int64) // 3) + 1,
        "ss_quantity": sc["quantity"],
        "ss_wholesale_cost": sc["wholesale_cost"],
        "ss_list_price": sc["list_price"],
        "ss_sales_price": sc["sales_price"],
        "ss_ext_discount_amt": sc["ext_discount_amt"],
        "ss_ext_sales_price": sc["ext_sales_price"],
        "ss_ext_wholesale_cost": sc["ext_wholesale_cost"],
        "ss_ext_list_price": sc["ext_list_price"],
        "ss_ext_tax": sc["ext_tax"],
        "ss_coupon_amt": sc["coupon_amt"],
        "ss_net_paid": sc["net_paid"],
        "ss_net_paid_inc_tax": sc["net_paid_inc_tax"],
        "ss_net_profit": sc["net_profit"],
    })
    # returns reference real sales rows for key consistency
    n_sr = max(n_ss // 10, 20)
    ridx = rng.choice(n_ss, n_sr, replace=False)
    store_returns = pd.DataFrame({
        "sr_returned_date_sk": np.minimum(
            store_sales["ss_sold_date_sk"].to_numpy()[ridx]
            + rng.randint(1, 120, n_sr), nd).astype(np.int64),
        "sr_return_time_sk": store_sales["ss_sold_time_sk"].to_numpy()[ridx],
        "sr_item_sk": store_sales["ss_item_sk"].to_numpy()[ridx],
        "sr_customer_sk": store_sales["ss_customer_sk"].to_numpy()[ridx],
        "sr_cdemo_sk": rng.randint(1, n_cd + 1, n_sr).astype(np.int64),
        "sr_hdemo_sk": rng.randint(1, n_hd + 1, n_sr).astype(np.int64),
        "sr_addr_sk": rng.randint(1, n_addr + 1, n_sr).astype(np.int64),
        "sr_store_sk": np.nan_to_num(
            store_sales["ss_store_sk"].to_numpy()[ridx], nan=1.0
        ).astype(np.int64),
        "sr_reason_sk": rng.randint(1, 36, n_sr).astype(np.int64),
        "sr_ticket_number": store_sales["ss_ticket_number"].to_numpy()[ridx],
        "sr_return_quantity": rng.randint(1, 50, n_sr).astype(np.int64),
        "sr_return_amt": np.round(rng.uniform(1, 20000, n_sr), 2),
        "sr_return_tax": np.round(rng.uniform(0, 100, n_sr), 2),
        "sr_return_amt_inc_tax": np.round(rng.uniform(1, 1100, n_sr), 2),
        "sr_fee": np.round(rng.uniform(1, 100, n_sr), 2),
        "sr_return_ship_cost": np.round(rng.uniform(0, 500, n_sr), 2),
        "sr_refunded_cash": np.round(rng.uniform(0, 1000, n_sr), 2),
        "sr_reversed_charge": np.round(rng.uniform(0, 1000, n_sr), 2),
        "sr_store_credit": np.round(rng.uniform(0, 1000, n_sr), 2),
        "sr_net_loss": np.round(rng.uniform(1, 1000, n_sr), 2),
    })

    n_cs = max(scale_rows // 2, 100)
    cc2 = sales_common(n_cs)
    catalog_sales = pd.DataFrame({
        "cs_sold_date_sk": cc2["sold_date_sk"],
        "cs_sold_time_sk": cc2["sold_time_sk"],
        "cs_ship_date_sk": np.minimum(cc2["sold_date_sk"]
                                      + rng.randint(1, 130, n_cs), nd
                                      ).astype(np.int64),
        "cs_bill_customer_sk": rng.randint(1, n_cust + 1, n_cs).astype(np.int64),
        "cs_bill_cdemo_sk": rng.randint(1, n_cd + 1, n_cs).astype(np.int64),
        "cs_bill_hdemo_sk": rng.randint(1, n_hd + 1, n_cs).astype(np.int64),
        "cs_bill_addr_sk": rng.randint(1, n_addr + 1, n_cs).astype(np.int64),
        "cs_ship_customer_sk": rng.randint(1, n_cust + 1, n_cs).astype(np.int64),
        "cs_ship_cdemo_sk": rng.randint(1, n_cd + 1, n_cs).astype(np.int64),
        "cs_ship_hdemo_sk": rng.randint(1, n_hd + 1, n_cs).astype(np.int64),
        "cs_ship_addr_sk": _null_some(rng, rng.randint(1, n_addr + 1, n_cs), 0.02),
        "cs_call_center_sk": rng.randint(1, n_cc + 1, n_cs).astype(np.int64),
        "cs_catalog_page_sk": rng.randint(1, n_cp + 1, n_cs).astype(np.int64),
        "cs_ship_mode_sk": rng.randint(1, 21, n_cs).astype(np.int64),
        "cs_warehouse_sk": rng.randint(1, n_wh + 1, n_cs).astype(np.int64),
        "cs_item_sk": cc2["item_sk"],
        "cs_promo_sk": rng.randint(1, n_promo + 1, n_cs).astype(np.int64),
        "cs_order_number": (np.arange(n_cs, dtype=np.int64) // 2) + 1,
        "cs_quantity": cc2["quantity"],
        "cs_wholesale_cost": cc2["wholesale_cost"],
        "cs_list_price": cc2["list_price"],
        "cs_sales_price": cc2["sales_price"],
        "cs_ext_discount_amt": cc2["ext_discount_amt"],
        "cs_ext_sales_price": cc2["ext_sales_price"],
        "cs_ext_wholesale_cost": cc2["ext_wholesale_cost"],
        "cs_ext_list_price": cc2["ext_list_price"],
        "cs_ext_tax": cc2["ext_tax"],
        "cs_coupon_amt": cc2["coupon_amt"],
        "cs_ext_ship_cost": np.round(rng.uniform(0, 500, n_cs), 2),
        "cs_net_paid": cc2["net_paid"],
        "cs_net_paid_inc_tax": cc2["net_paid_inc_tax"],
        "cs_net_profit": cc2["net_profit"],
    })
    n_cr = max(n_cs // 10, 10)
    ridx = rng.choice(n_cs, n_cr, replace=False)
    catalog_returns = pd.DataFrame({
        "cr_returned_date_sk": np.minimum(
            catalog_sales["cs_sold_date_sk"].to_numpy()[ridx]
            + rng.randint(1, 120, n_cr), nd).astype(np.int64),
        "cr_returned_time_sk": catalog_sales["cs_sold_time_sk"].to_numpy()[ridx],
        "cr_item_sk": catalog_sales["cs_item_sk"].to_numpy()[ridx],
        "cr_refunded_customer_sk": rng.randint(1, n_cust + 1, n_cr).astype(np.int64),
        "cr_refunded_cdemo_sk": rng.randint(1, n_cd + 1, n_cr).astype(np.int64),
        "cr_refunded_hdemo_sk": rng.randint(1, n_hd + 1, n_cr).astype(np.int64),
        "cr_refunded_addr_sk": rng.randint(1, n_addr + 1, n_cr).astype(np.int64),
        "cr_returning_customer_sk": rng.randint(1, n_cust + 1, n_cr).astype(np.int64),
        "cr_returning_cdemo_sk": rng.randint(1, n_cd + 1, n_cr).astype(np.int64),
        "cr_returning_hdemo_sk": rng.randint(1, n_hd + 1, n_cr).astype(np.int64),
        "cr_returning_addr_sk": rng.randint(1, n_addr + 1, n_cr).astype(np.int64),
        "cr_call_center_sk": rng.randint(1, n_cc + 1, n_cr).astype(np.int64),
        "cr_catalog_page_sk": rng.randint(1, n_cp + 1, n_cr).astype(np.int64),
        "cr_ship_mode_sk": rng.randint(1, 21, n_cr).astype(np.int64),
        "cr_warehouse_sk": rng.randint(1, n_wh + 1, n_cr).astype(np.int64),
        "cr_reason_sk": rng.randint(1, 36, n_cr).astype(np.int64),
        "cr_order_number": catalog_sales["cs_order_number"].to_numpy()[ridx],
        "cr_return_quantity": rng.randint(1, 50, n_cr).astype(np.int64),
        "cr_return_amount": np.round(rng.uniform(1, 20000, n_cr), 2),
        "cr_return_tax": np.round(rng.uniform(0, 100, n_cr), 2),
        "cr_return_amt_inc_tax": np.round(rng.uniform(1, 1100, n_cr), 2),
        "cr_fee": np.round(rng.uniform(1, 100, n_cr), 2),
        "cr_return_ship_cost": np.round(rng.uniform(0, 500, n_cr), 2),
        "cr_refunded_cash": np.round(rng.uniform(0, 1000, n_cr), 2),
        "cr_reversed_charge": np.round(rng.uniform(0, 1000, n_cr), 2),
        "cr_store_credit": np.round(rng.uniform(0, 1000, n_cr), 2),
        "cr_net_loss": np.round(rng.uniform(1, 1000, n_cr), 2),
    })

    n_ws = max(scale_rows // 2, 100)
    wc = sales_common(n_ws)
    web_sales = pd.DataFrame({
        "ws_sold_date_sk": wc["sold_date_sk"],
        "ws_sold_time_sk": wc["sold_time_sk"],
        "ws_ship_date_sk": np.minimum(wc["sold_date_sk"]
                                      + rng.randint(1, 130, n_ws), nd
                                      ).astype(np.int64),
        "ws_item_sk": wc["item_sk"],
        "ws_bill_customer_sk": rng.randint(1, n_cust + 1, n_ws).astype(np.int64),
        "ws_bill_cdemo_sk": rng.randint(1, n_cd + 1, n_ws).astype(np.int64),
        "ws_bill_hdemo_sk": rng.randint(1, n_hd + 1, n_ws).astype(np.int64),
        "ws_bill_addr_sk": rng.randint(1, n_addr + 1, n_ws).astype(np.int64),
        "ws_ship_customer_sk": _null_some(
            rng, rng.randint(1, n_cust + 1, n_ws), 0.02),
        "ws_ship_cdemo_sk": rng.randint(1, n_cd + 1, n_ws).astype(np.int64),
        "ws_ship_hdemo_sk": rng.randint(1, n_hd + 1, n_ws).astype(np.int64),
        "ws_ship_addr_sk": rng.randint(1, n_addr + 1, n_ws).astype(np.int64),
        "ws_web_page_sk": rng.randint(1, n_wp + 1, n_ws).astype(np.int64),
        "ws_web_site_sk": rng.randint(1, n_web + 1, n_ws).astype(np.int64),
        "ws_ship_mode_sk": rng.randint(1, 21, n_ws).astype(np.int64),
        "ws_warehouse_sk": rng.randint(1, n_wh + 1, n_ws).astype(np.int64),
        "ws_promo_sk": rng.randint(1, n_promo + 1, n_ws).astype(np.int64),
        "ws_order_number": (np.arange(n_ws, dtype=np.int64) // 2) + 1,
        "ws_quantity": wc["quantity"],
        "ws_wholesale_cost": wc["wholesale_cost"],
        "ws_list_price": wc["list_price"],
        "ws_sales_price": wc["sales_price"],
        "ws_ext_discount_amt": wc["ext_discount_amt"],
        "ws_ext_sales_price": wc["ext_sales_price"],
        "ws_ext_wholesale_cost": wc["ext_wholesale_cost"],
        "ws_ext_list_price": wc["ext_list_price"],
        "ws_ext_tax": wc["ext_tax"],
        "ws_coupon_amt": wc["coupon_amt"],
        "ws_ext_ship_cost": np.round(rng.uniform(0, 500, n_ws), 2),
        "ws_net_paid": wc["net_paid"],
        "ws_net_paid_inc_tax": wc["net_paid_inc_tax"],
        "ws_net_profit": wc["net_profit"],
    })
    n_wr = max(n_ws // 10, 10)
    ridx = rng.choice(n_ws, n_wr, replace=False)
    web_returns = pd.DataFrame({
        "wr_returned_date_sk": np.minimum(
            web_sales["ws_sold_date_sk"].to_numpy()[ridx]
            + rng.randint(1, 120, n_wr), nd).astype(np.int64),
        "wr_returned_time_sk": web_sales["ws_sold_time_sk"].to_numpy()[ridx],
        "wr_item_sk": web_sales["ws_item_sk"].to_numpy()[ridx],
        "wr_refunded_customer_sk": rng.randint(1, n_cust + 1, n_wr).astype(np.int64),
        "wr_refunded_cdemo_sk": rng.randint(1, n_cd + 1, n_wr).astype(np.int64),
        "wr_refunded_hdemo_sk": rng.randint(1, n_hd + 1, n_wr).astype(np.int64),
        "wr_refunded_addr_sk": rng.randint(1, n_addr + 1, n_wr).astype(np.int64),
        "wr_returning_customer_sk": rng.randint(1, n_cust + 1, n_wr).astype(np.int64),
        "wr_returning_cdemo_sk": rng.randint(1, n_cd + 1, n_wr).astype(np.int64),
        "wr_returning_hdemo_sk": rng.randint(1, n_hd + 1, n_wr).astype(np.int64),
        "wr_returning_addr_sk": rng.randint(1, n_addr + 1, n_wr).astype(np.int64),
        "wr_web_page_sk": rng.randint(1, n_wp + 1, n_wr).astype(np.int64),
        "wr_reason_sk": rng.randint(1, 36, n_wr).astype(np.int64),
        "wr_order_number": web_sales["ws_order_number"].to_numpy()[ridx],
        "wr_return_quantity": rng.randint(1, 50, n_wr).astype(np.int64),
        "wr_return_amt": np.round(rng.uniform(1, 20000, n_wr), 2),
        "wr_return_tax": np.round(rng.uniform(0, 100, n_wr), 2),
        "wr_return_amt_inc_tax": np.round(rng.uniform(1, 1100, n_wr), 2),
        "wr_fee": np.round(rng.uniform(1, 100, n_wr), 2),
        "wr_return_ship_cost": np.round(rng.uniform(0, 500, n_wr), 2),
        "wr_refunded_cash": np.round(rng.uniform(0, 1000, n_wr), 2),
        "wr_reversed_charge": np.round(rng.uniform(0, 1000, n_wr), 2),
        "wr_account_credit": np.round(rng.uniform(0, 1000, n_wr), 2),
        "wr_net_loss": np.round(rng.uniform(1, 1000, n_wr), 2),
    })

    n_inv = max(scale_rows // 2, 200)
    inventory = pd.DataFrame({
        "inv_date_sk": rng.randint(1, nd + 1, n_inv).astype(np.int64),
        "inv_item_sk": rng.randint(1, n_item + 1, n_inv).astype(np.int64),
        "inv_warehouse_sk": rng.randint(1, n_wh + 1, n_inv).astype(np.int64),
        "inv_quantity_on_hand": rng.randint(0, 1000, n_inv).astype(np.int64),
    })

    return {
        "store_sales": store_sales,
        "store_returns": store_returns,
        "catalog_sales": catalog_sales,
        "catalog_returns": catalog_returns,
        "web_sales": web_sales,
        "web_returns": web_returns,
        "inventory": inventory,
        "date_dim": date_dim,
        "time_dim": time_dim,
        "item": item,
        "customer": customer,
        "customer_address": customer_address,
        "customer_demographics": customer_demographics,
        "household_demographics": household_demographics,
        "income_band": income_band,
        "store": store,
        "warehouse": warehouse,
        "promotion": promotion,
        "call_center": call_center,
        "catalog_page": catalog_page,
        "web_site": web_site,
        "web_page": web_page,
        "reason": reason,
        "ship_mode": ship_mode,
    }
