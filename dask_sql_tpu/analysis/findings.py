"""Finding records shared by the plan verifier and the self-lint.

Import-light on purpose (no jax, no planner imports): the resilience error
taxonomy and the CLI both consume these without pulling the engine in.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import FrozenSet

SEV_ERROR = "error"  # engine inconsistency: raises taxonomy PlanError at bind
SEV_WARN = "warn"    # statically-doomed rung / suspect construct; strict raises
SEV_INFO = "info"    # advisory (shape buckets, recompile hazards)

_SEV_ORDER = {SEV_ERROR: 0, SEV_WARN: 1, SEV_INFO: 2}


@dataclass(frozen=True)
class Finding:
    """One plan-verifier finding, displayed by ``EXPLAIN LINT``."""

    rule: str       # stable rule id, e.g. "dtype-mismatch", "radix-overflow"
    severity: str   # SEV_ERROR | SEV_WARN | SEV_INFO
    node: str       # plan node label the finding anchors to
    message: str
    #: compiled ladder rungs this finding proves doomed (skipped, not attempted)
    rungs: FrozenSet[str] = field(default_factory=frozenset)

    def format(self) -> str:
        return f"{self.severity}[{self.rule}] {self.node}: {self.message}"


def sort_findings(findings):
    return sorted(findings, key=lambda f: (_SEV_ORDER.get(f.severity, 9),
                                           f.rule, f.node, f.message))
