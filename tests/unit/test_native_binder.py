"""Native (C++) binder: differential bound-plan equality vs the Python binder.

Parity: the reference's entire bind stage is compiled (SqlToRel driven from
src/sql.rs:586-674); here native/binder.cpp parses AND binds in one native
call, emitting a flat plan buffer that must decode to EXACTLY the
plan.py/expressions.py objects the Python binder builds — checked
structurally over the TPC-H corpus fallback-OFF (a native miss there is a
failure, not a skip), the TPC-DS corpus, and targeted grammar cases.
"""
import dataclasses

import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.planner import plan as p
from dask_sql_tpu.planner.binder import BindError, Binder
from dask_sql_tpu.planner.expressions import Expr, SortKey, WindowSpec
from dask_sql_tpu.planner.native_bridge import native_bind, native_parse
from dask_sql_tpu.planner.parser import parse_sql

from tests.tpch import QUERIES as TPCH_QUERIES, generate as tpch_generate
from tests.tpcds_queries import QUERIES as TPCDS_QUERIES

native_available = native_parse("SELECT 1") is not None
needs_native = pytest.mark.skipif(not native_available,
                                  reason="native library not built")


# ---------------------------------------------------------------- comparator
def plans_equal(a, b, path="plan"):
    """Deep structural equality over plan nodes (eq=False identity classes)
    and expressions (frozen dataclasses, except plan-valued fields which
    recurse here).  Returns (ok, why)."""
    return _eq(a, b, path)


def _eq(a, b, path):
    if isinstance(a, p.LogicalPlan) or isinstance(b, p.LogicalPlan):
        if type(a) is not type(b):
            return False, f"{path}: {type(a).__name__} != {type(b).__name__}"
        for f in dataclasses.fields(a):
            ok, why = _eq(getattr(a, f.name), getattr(b, f.name),
                          f"{path}.{f.name}")
            if not ok:
                return ok, why
        return True, ""
    if isinstance(a, Expr) or isinstance(b, Expr):
        if type(a) is not type(b):
            return False, f"{path}: {type(a).__name__} != {type(b).__name__}"
        for f in dataclasses.fields(a):
            ok, why = _eq(getattr(a, f.name), getattr(b, f.name),
                          f"{path}.{f.name}")
            if not ok:
                return ok, why
        return True, ""
    if isinstance(a, (SortKey, WindowSpec)) or isinstance(b, (SortKey, WindowSpec)):
        if type(a) is not type(b):
            return False, f"{path}: {type(a).__name__} != {type(b).__name__}"
        for f in dataclasses.fields(a):
            ok, why = _eq(getattr(a, f.name), getattr(b, f.name),
                          f"{path}.{f.name}")
            if not ok:
                return ok, why
        return True, ""
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        if len(a) != len(b):
            return False, f"{path}: length {len(a)} != {len(b)}"
        for i, (x, y) in enumerate(zip(a, b)):
            ok, why = _eq(x, y, f"{path}[{i}]")
            if not ok:
                return ok, why
        return True, ""
    if a != b:
        return False, f"{path}: {a!r} != {b!r}"
    return True, ""


# ---------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def tpch_ctx():
    c = Context()
    for name, df in tpch_generate(scale_rows=50).items():
        c.create_table(name, df)
    return c


@pytest.fixture(scope="module")
def tpcds_ctx():
    from tests.tpcds import generate

    c = Context()
    for name, df in generate(scale_rows=1000).items():
        c.create_table(name, df)
    return c


def _differential(c, sql, require_native=False):
    catalog = c._prepare_catalog()
    nat = native_bind(sql, catalog)
    if nat is None:
        if require_native:
            pytest.fail("fell back to the Python binder")
        pytest.skip("native binder declined")
    ref = Binder(catalog).bind_statement(parse_sql(sql)[0])
    ok, why = plans_equal(nat, ref)
    assert ok, why


# ---------------------------------------------------------------- corpora
@needs_native
@pytest.mark.parametrize("qnum", sorted(TPCH_QUERIES))
def test_tpch_binds_natively(tpch_ctx, qnum):
    """Fallback-off: every TPC-H query must bind through the C++ binder."""
    _differential(tpch_ctx, TPCH_QUERIES[qnum], require_native=True)


@needs_native
def test_tpcds_corpus_differential(tpcds_ctx):
    misses, mismatches = [], []
    catalog = tpcds_ctx._prepare_catalog()
    for qnum, sql in sorted(TPCDS_QUERIES.items()):
        try:
            nat = native_bind(sql, catalog)
        except (BindError, KeyError) as e:
            nat = f"error:{type(e).__name__}"
        if nat is None:
            misses.append(qnum)
            continue
        try:
            ref = Binder(catalog).bind_statement(parse_sql(sql)[0])
        except (BindError, KeyError) as e:
            ref = f"error:{type(e).__name__}"
        if isinstance(nat, str) or isinstance(ref, str):
            if nat != ref:
                mismatches.append((qnum, f"error surface: {nat} != {ref}"))
            continue
        ok, why = plans_equal(nat, ref)
        if not ok:
            mismatches.append((qnum, why))
    assert not mismatches, f"bound-plan mismatches: {mismatches[:5]}"
    assert not misses, f"native misses: {misses}"


GRAMMAR_CASES = [
    "SELECT a, a + 1 AS c FROM t WHERE x > 5 AND y LIKE 'a%'",
    "SELECT DISTINCT t.a FROM t JOIN u USING (k)",
    "SELECT * FROM t NATURAL JOIN s",
    "SELECT t.*, s.x AS sx FROM t, s WHERE t.k = s.k AND t.a < s.x",
    "WITH c AS (SELECT a AS x FROM t) SELECT * FROM c WHERE x > "
    "(SELECT AVG(x) FROM c)",
    "SELECT CASE a WHEN 1 THEN 'x' ELSE 'y' END FROM t",
    "SELECT CAST(a AS DOUBLE), TRY_CAST(y AS BIGINT) FROM t",
    "SELECT SUM(a) FILTER (WHERE x > 0), COUNT(DISTINCT k) FROM t",
    "SELECT k, SUM(a) AS s FROM t GROUP BY k HAVING SUM(a) > 10 ORDER BY s DESC",
    "SELECT k, SUM(a) FROM t GROUP BY 1 ORDER BY 2 DESC NULLS FIRST LIMIT 5",
    "SELECT a, ROW_NUMBER() OVER (PARTITION BY k ORDER BY a) FROM t",
    "SELECT SUM(a) OVER (ORDER BY x ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) FROM t",
    "SELECT a FROM t WHERE k IN (SELECT k FROM s) AND x NOT IN (1, 2)",
    "SELECT a FROM t WHERE EXISTS (SELECT 1 FROM s WHERE s.k = t.k)",
    "SELECT a FROM t UNION SELECT x FROM s ORDER BY 1 LIMIT 3",
    "SELECT a FROM t INTERSECT SELECT x FROM s",
    "SELECT a FROM t EXCEPT ALL SELECT x FROM s",
    "VALUES (1, 'a'), (2, NULL)",
    "SELECT EXTRACT(YEAR FROM d), d + INTERVAL '3' DAY FROM t",
    "SELECT SUBSTRING(y FROM 2 FOR 3), TRIM(y), UPPER(y) || 'z' FROM t",
    "SELECT a BETWEEN 1 AND 5, a NOT BETWEEN SYMMETRIC 5 AND 1 FROM t",
    "SELECT x IS NULL, x IS NOT NULL, a IS DISTINCT FROM x FROM t",
    "SELECT k, GROUPING(k) FROM t GROUP BY ROLLUP (k)",
    "SELECT k, x, SUM(a) FROM t GROUP BY GROUPING SETS ((k, x), (k), ())",
    "SELECT COALESCE(x, 0), NULLIF(a, 1), GREATEST(a, x) FROM t",
    "SELECT * FROM (SELECT a AS z FROM t) sub (w) WHERE w > 1",
    "SELECT a FROM t ORDER BY a DESC, x ASC NULLS LAST OFFSET 2",
    "SELECT 1 + 1",
    "EXPLAIN SELECT a FROM t WHERE x > 1",
    "SELECT smp.a FROM t TABLESAMPLE SYSTEM (10) AS smp",
    "SELECT k FROM t WHERE d <= DATE '1998-09-02' AND ts < "
    "TIMESTAMP '2020-06-01 12:30:00'",
    "SELECT AVG(a) OVER w, MIN(x) OVER w FROM t WINDOW w AS "
    "(PARTITION BY k ORDER BY a)",
    "SELECT a / 2, a % 3, -a, NOT (x > 1) FROM t",
    "SELECT * FROM PREDICT(MODEL my_model, SELECT a, k FROM t) AS pr",
]


@needs_native
@pytest.mark.parametrize("idx", range(len(GRAMMAR_CASES)))
def test_grammar_case(idx):
    c = Context()
    c.create_table("t", pd.DataFrame({
        "a": [1, 2, 3],
        "k": [1, 1, 2],
        "x": [1.5, None, 2.5],
        "y": ["p", "q", "r"],
        "d": pd.to_datetime(["2020-01-01", "2021-02-03", "2022-03-04"]),
        "ts": pd.to_datetime(["2020-01-01 10:00", "2021-02-03 11:30",
                              "2022-03-04 23:59"]),
    }))
    c.create_table("s", pd.DataFrame({"k": [1, 2], "x": [10.0, 20.0]}))
    c.create_table("u", pd.DataFrame({"k": [1], "z": [5]}))
    _differential(c, GRAMMAR_CASES[idx], require_native=True)


@needs_native
def test_udf_binding_differential():
    c = Context()
    c.create_table("t", pd.DataFrame({"a": [1, 2, 3]}))
    c.register_function(lambda v: v + 1, "incr", [("v", np.int64)], np.int64)
    _differential(c, "SELECT incr(a) FROM t", require_native=True)


@needs_native
def test_bind_errors_match():
    """Error class AND message agree with the Python binder (incl. the
    KeyError surface for missing tables the integration tests pin)."""
    c = Context()
    c.create_table("t", pd.DataFrame({"a": [1]}))
    catalog = c._prepare_catalog()
    for sql in ["SELECT nope FROM t",
                "SELECT a FROM t GROUP BY a HAVING b > 1",
                "SELECT missing_fn(a) FROM t",
                "SELECT a FROM missing_table",
                "SELECT t.a FROM t JOIN t AS t2 ON t.a = t2.a WHERE a > 0"]:
        try:
            Binder(catalog).bind_statement(parse_sql(sql)[0])
            expected = None
        except (BindError, KeyError) as e:
            expected = (type(e), str(e))
        try:
            got_plan = native_bind(sql, catalog)
            assert got_plan is not None, f"native binder declined: {sql}"
            got = None
        except (BindError, KeyError) as e:
            got = (type(e), str(e))
        assert got == expected, f"{sql}: {got} != {expected}"


@needs_native
def test_end_to_end_native_binder_values(tpch_ctx):
    """The engine path (Context.sql with sql.native.binder=auto) must give
    the same values as the Python-binder path for a representative query."""
    sql = TPCH_QUERIES[1]
    on = tpch_ctx.sql(sql, return_futures=False,
                      config_options={"sql.native.binder": "on"})
    off = tpch_ctx.sql(sql, return_futures=False,
                       config_options={"sql.native.binder": "off"})
    pd.testing.assert_frame_equal(on.reset_index(drop=True),
                                  off.reset_index(drop=True))
